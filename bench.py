"""Headline benchmark: ec_jax RS k=8,m=3 on 4 MiB stripes (BASELINE config #2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

- value: on-chip encode throughput (GiB/s of data bytes consumed) for the
  GF(2^8) MXU matmul, batched over stripes, steady state.
- vs_baseline: ratio against the host CPU path (native C++ table-driven GF
  region ops — the scalar-jerasure equivalent — measured on this machine).

Measurement note: the axon TPU tunnel makes per-call timing unreliable
(block_until_ready returns early; a host fetch pays ~0.5s RPC latency), so
device time is measured by chaining N data-dependent encodes inside one jit
and differencing two loop lengths — RPC overhead and the final fetch cancel.

Details (decode, CPU numbers) go to bench_details.json; the driver contract
is the one line.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.models import reed_solomon as rs
    from ceph_tpu.ops import gf
    from ceph_tpu import native

    k, m = 8, 3
    chunk = 512 * 1024          # 4 MiB stripe = k * 512 KiB
    batch = 16                  # stripes per dispatch (64 MiB data)
    matrix = rs.reed_sol_van_matrix(k, m)
    mbits = jnp.asarray(gf.gf_matrix_to_bits(matrix))

    rng = np.random.default_rng(0)
    data_host = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    data = jax.device_put(jnp.asarray(data_host))
    data_bytes = batch * k * chunk

    @functools.partial(jax.jit, static_argnames=("n", "rows"))
    def loop(mb, d, n, rows):
        # data-dependent chain of encodes; scalar out forces completion
        def body(_, carry):
            p = gf.gf2_matmul_bytes(mb, carry)
            return carry.at[:, :rows, :].set(p)

        return jax.lax.fori_loop(0, n, body, d).astype(jnp.int32).sum()

    def device_seconds_per_encode(mb, d, rows, n=201, iters=5):
        for nn in (1, n):
            float(loop(mb, d, nn, rows))  # compile + warm
        def t(nn):
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                float(loop(mb, d, nn, rows))
                best = min(best, time.perf_counter() - t0)
            return best
        return (t(n) - t(1)) / (n - 1)

    t_enc = device_seconds_per_encode(mbits, data, rows=m)
    enc_gibs = data_bytes / t_enc / (1 << 30)

    # single-erasure decode: rebuild data chunk 0 from chunks 1..k-1 + p0;
    # survivors carried as a (B, k, S) buffer, same matmul shape family
    have = list(range(1, k)) + [k]
    dmat = rs.decode_matrix(matrix, k, [0], have)
    dmat_bits = jnp.asarray(gf.gf_matrix_to_bits(dmat))
    t_dec = device_seconds_per_encode(dmat_bits, data, rows=1)
    dec_gibs = data_bytes / t_dec / (1 << 30)

    # CPU baseline: native C++ table-driven GF matmul, one stripe
    lib = native.get_lib()
    cpu_gibs = None
    if lib is not None:
        import ctypes

        tables = np.zeros((m * k, 256), dtype=np.uint8)
        for j in range(m):
            for i in range(k):
                tables[j * k + i] = gf.gf_mul(
                    np.full(256, matrix[j, i], np.uint8),
                    np.arange(256, dtype=np.uint8))
        one = np.ascontiguousarray(data_host[0])
        out = np.zeros((m, chunk), dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        def cpu_once():
            lib.ceph_tpu_gf_matmul(
                tables.ctypes.data_as(u8p), m, k,
                one.ctypes.data_as(u8p), chunk,
                out.ctypes.data_as(u8p))

        cpu_once()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cpu_once()
            best = min(best, time.perf_counter() - t0)
        cpu_gibs = (k * chunk) / best / (1 << 30)

    # None (JSON null) when no native CPU baseline could be measured here —
    # distinguishable from a measured ratio of exactly 1.0
    vs_baseline = (enc_gibs / cpu_gibs) if cpu_gibs else None

    details = {
        "encode_gibs": enc_gibs,
        "decode_single_erasure_gibs": dec_gibs,
        "cpu_native_gibs": cpu_gibs,
        "encode_ms_per_batch": t_enc * 1e3,
        "k": k, "m": m, "chunk_bytes": chunk, "batch": batch,
        "backend": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }
    with open("bench_details.json", "w") as f:
        json.dump(details, f, indent=2)

    print(json.dumps({
        "metric": "ec_jax_encode_k8m3_4MiB_stripe",
        "value": round(enc_gibs, 3),
        "unit": "GiB/s",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline else None,
    }))


if __name__ == "__main__":
    main()

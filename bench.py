"""Headline benchmark: ec_jax RS k=8,m=3 on 4 MiB stripes (BASELINE config #2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

- value: on-chip encode throughput (GiB/s of data bytes consumed) for the
  packed-word xtime Pallas kernel (ops/gf_pallas.py) — the default device
  path gf.gf_matmul_device dispatches on TPU — batched over stripes,
  steady state on the device-native int32 word layout.  Bit-exactness
  against the host SIMD oracle is asserted before timing.
- vs_baseline: ratio against the host CPU path (native C++ SIMD split-table
  GF region ops — the jerasure-SSE/isa-l speed tier, measured here).

Measurement note: the axon TPU tunnel makes per-call timing unreliable
(block_until_ready returns early; a host fetch pays ~0.5s RPC latency), so
device time is measured by chaining N data-dependent encodes inside one jit
and differencing two loop lengths — RPC overhead and the final fetch cancel.

Details (decode sweep over 1..m erasures, XLA-path and CPU numbers) go to
bench_details.json; the driver contract is the one line.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time
from typing import Optional, Tuple

import numpy as np

# CEPH_TPU_BENCH_SMOKE=1: tiny shapes, headline only (tests drive the
# contract path end-to-end without paying a real measurement)
_SMOKE = os.environ.get("CEPH_TPU_BENCH_SMOKE") == "1"

_CONTRACT_METRIC = "ec_jax_encode_k8m3_4MiB_stripe"
_contract_emitted = False
# the watchdog thread and the bench body race to emit exactly once
import threading as _threading  # noqa: E402

_contract_lock = _threading.Lock()

# Wall-clock budget (the BENCH_r05 rc=124 fix): the bench must finish
# under the harness timeout, so optional sections are skipped — with a
# `truncated` flag in the contract line — once the clock runs low.
_T0 = time.monotonic()


def _budget_seconds() -> float:
    return float(os.environ.get("CEPH_TPU_BENCH_BUDGET", "780"))


def _remaining() -> float:
    return _budget_seconds() - (time.monotonic() - _T0)


def _emit_contract(value: Optional[float],
                   vs_baseline: Optional[float],
                   plan_cache: Optional[dict] = None,
                   encode_service: Optional[dict] = None,
                   tier: Optional[dict] = None,
                   device_health: Optional[dict] = None,
                   tail: Optional[dict] = None,
                   load: Optional[dict] = None,
                   durability: Optional[dict] = None,
                   mesh: Optional[dict] = None,
                   multihost: Optional[dict] = None,
                   trace: Optional[dict] = None,
                   group_commit: Optional[dict] = None,
                   compute: Optional[dict] = None,
                   xsched: Optional[dict] = None,
                   spmd: Optional[dict] = None,
                   repair: Optional[dict] = None,
                   inference: Optional[dict] = None,
                   chaos: Optional[dict] = None,
                   truncated: bool = False) -> None:
    """Print the one-line JSON driver contract, exactly once, before
    any optional extended benches run — a wedged tunnel or a crashed
    secondary bench can no longer yield an empty bench.  plan_cache
    carries the ExecPlan hit/miss/retrace counters, encode_service the
    micro-batching service probe counters, tier the hot-set/read-tier
    probe counters, device_health the circuit-breaker fault-tolerance
    probe (forced-failure host fallback bit-exact, trip -> probe ->
    recovered), tail the hedged-read scheduler probe (first-k
    completion under an injected straggler, cancellation-clean), load
    the open-loop multi-tenant harness probe (goodput + streaming
    p50/p95/p99 over the embedded cluster, deterministic schedules),
    durability the crash-consistency probe (smoke power-cut sweep over
    TPUStore: crash points explored, zero invariant violations, and
    the deliberately-broken store caught as a self-test), mesh the
    multi-chip mesh probe (same batch bit-exact through 1-device /
    N-device / host oracle, sick chip shrinks the mesh with zero host
    fallbacks), multihost the cross-host data-plane probe (bit-exact
    encode across a real >=2-process jax.distributed group on the
    hybrid DCN x ICI mesh, plus the host-loss leg: one host:<id>
    event retires all the host's chips together, one shrink, zero
    host fallbacks), trace the critical-path tracing probe (reducer
    correctness + spans-on-vs-off overhead at sample rate 0), compute
    the coded-compute probe (every linear kernel first-k
    result-domain-decode bit-exact on a parity-including shard
    subset + the hedged straggler leg), xsched the codec-compiler
    probe (schedule-vs-naive bit-exactness over the bitmatrix family
    + decode submatrices + a GF bit expansion, with the measured
    XOR-count reduction and memo hits), spmd the collective-safety
    cross-check (static collective-site map non-empty, the 2-process
    smoke leg's runtime-observed collective trace ⊆ the static map,
    per-process order congruence), repair the MSR regenerating-codec
    probe (every single-erasure pattern rebuilt bit-exact from d
    beta-fragments, with the measured bytes-read-per-repaired-byte
    ratio vs the classic k-read), inference the coded inference
    serving probe (exact combine bit-identical to the host oracle,
    every single-shard-loss pattern served from the Fisher-fused
    substitutes within the error budget, the hedged sub-infer
    straggler leg completing from the first structurally-sufficient
    arrival set), chaos the compound-chaos probe (a seeded composed
    3-hazard scenario — stragglers x device faults x kill-switch
    flips — over live multi-tenant traffic with every invariant
    monitor armed: the seed is echoed so any violation replays, and
    violations must be 0);
    truncated flags a budget-shortened run.  Thread-safe:
    the deadline watchdog and the bench body may race to emit."""
    global _contract_emitted
    with _contract_lock:
        if _contract_emitted:
            return
        _contract_emitted = True
        print(json.dumps({
            "metric": _CONTRACT_METRIC,
            "value": round(value, 3) if value is not None else None,
            "unit": "GiB/s",
            "vs_baseline": round(vs_baseline, 2) if vs_baseline
            else None,
            "plan_cache": plan_cache,
            "encode_service": encode_service,
            "tier": tier,
            "device_health": device_health,
            "tail": tail,
            "load": load,
            "durability": durability,
            "mesh": mesh,
            "multihost": multihost,
            "trace": trace,
            "group_commit": group_commit,
            "compute": compute,
            "xsched": xsched,
            "spmd": spmd,
            "repair": repair,
            "inference": inference,
            "chaos": chaos,
            "truncated": bool(truncated),
        }), flush=True)


def _arm_contract_watchdog() -> "_threading.Timer":
    """The BENCH_r05 rc=124 regression fix, second layer: even with
    every section budget-gated, a wedge inside a MANDATORY stage (jax
    import, the headline measurement) could still carry the process to
    the harness's outer `timeout` kill with no contract line.  A
    daemon timer fires shortly after the wall-clock budget expires and
    flushes a truncated null-value contract line — so whatever the
    outer timeout kills, the line is already out.  No-op when the
    bench emitted normally first (the emit is once-only and
    thread-safe)."""
    # margin: late enough that a healthy budget-0 smoke run always
    # emits normally first, early enough that budget(780)+margin stays
    # inside the harness's outer timeout (870 -k 10)
    margin = float(os.environ.get("CEPH_TPU_BENCH_WATCHDOG_MARGIN",
                                  "60"))
    delay = max(_remaining(), 0.0) + margin
    t = _threading.Timer(
        delay, lambda: _emit_contract(None, None, truncated=True))
    t.daemon = True
    t.start()
    return t


def _device_health_probe() -> Optional[dict]:
    """Pre-contract probe of the device-tier fault layer: with the
    injection seam forcing every dispatch to fail, an EC matmul must
    degrade to the bit-exact numpy host path (no exception reaches
    the caller) and trip the ec-encode breaker; with injection
    cleared, a forced half-open probe must re-close it.  Counters
    land in the contract line's device_health key; None (with a
    stderr note) when the probe cannot run.

    Contract-first discipline: every dispatch inside already rides
    device_call's own watchdog, so a wedged tunnel is bounded without
    an extra runner thread here."""
    if _remaining() < 0:
        print("# device health probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    prev = os.environ.get("CEPH_TPU_INJECT_DEVICE_FAIL")
    try:
        from ceph_tpu.common import circuit
        from ceph_tpu.ec import dispatch as ec_dispatch
        from ceph_tpu.models import reed_solomon as rs
        from ceph_tpu.ops import gf

        circuit.reset_all()
        mat = rs.reed_sol_van_matrix(4, 2)
        rng = np.random.default_rng(23)
        data = rng.integers(0, 256, (8, 4, 256), dtype=np.uint8)
        oracle = ec_dispatch.gf_matmul(mat, data, use_tpu=False)
        os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = "1.0"
        bitexact = 1
        for _ in range(4):   # past the trip threshold
            out = ec_dispatch.gf_matmul(mat, data, use_tpu=True,
                                        family="ec-encode")
            if not np.array_equal(out, oracle):
                bitexact = 0
        tripped = circuit.breaker("ec-encode").stats()
        # heal: clear injection, expire the backoff, one probe dispatch
        if prev is None:
            os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
        else:
            os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = prev
        circuit.breaker("ec-encode").force_probe()
        out = ec_dispatch.gf_matmul(mat, data, use_tpu=True,
                                    family="ec-encode")
        if not np.array_equal(out, oracle):
            bitexact = 0
        healed = circuit.breaker("ec-encode").stats()
        recovered = int(healed["state"] == "closed"
                        and healed["recoveries"] >= 1
                        and gf.backend_available())
        return {
            "bitexact": bitexact,
            "trips": tripped["trips"],
            "failures": tripped["failures"],
            "fallbacks": tripped["fallbacks"],
            "probes": healed["probes"],
            "recovered": recovered,
        }
    except Exception as e:
        print(f"# device health probe failed: {e!r}", file=sys.stderr)
        return None
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
        else:
            os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = prev
        try:
            from ceph_tpu.common import circuit

            circuit.reset_all()
        except Exception:
            pass


def _meshbench_subprocess(args: list, timeout_s: float
                          ) -> Optional[dict]:
    """Run ceph_tpu.parallel.meshbench in a SUBPROCESS and parse its
    one-line JSON.  A subprocess for two reasons: the CPU backend's
    device-count virtualization (XLA_FLAGS) must land before the
    backend initializes — too late in this process — and a wedged
    tunnel stays contained behind the hard timeout."""
    env = dict(os.environ)
    env.setdefault("CEPH_TPU_MESH_MIN_BYTES", "0")
    try:
        r = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.parallel.meshbench",
             *args],
            capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        print("# meshbench subprocess timed out (wedged?)",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        print(f"# meshbench failed rc={r.returncode}:"
              f" {r.stderr[-1000:]}", file=sys.stderr)
        return None
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    try:
        return json.loads(lines[-1]) if lines else None
    except json.JSONDecodeError:
        print(f"# meshbench emitted no JSON: {r.stdout[-500:]}",
              file=sys.stderr)
        return None


def _mesh_probe() -> Optional[dict]:
    """Pre-contract probe of the mesh-sharded EC data plane: the SAME
    stripe batch must be bit-identical through the single-device
    plan, the N-device mesh plan, and the host numpy oracle; then a
    scripted sick chip (sick=<id> injection) must shrink the mesh —
    per-device breaker tripped, survivors re-planned, output still
    bit-exact, ZERO host fallbacks.  Counters land in the contract
    line's `mesh` key (first-and-always under the PR-6 watchdog);
    None (with a stderr note) when the probe cannot run."""
    if _remaining() < 0:
        print("# mesh probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    timeout_s = float(os.environ.get(
        "CEPH_TPU_BENCH_MESH_PROBE_TIMEOUT", "120"))
    return _meshbench_subprocess(["--probe", "--smoke"], timeout_s)


def _multihost_probe() -> Optional[dict]:
    """Pre-contract probe of the cross-host data plane: a REAL
    2-process ``jax.distributed`` group (spawned by meshbench's
    ``--processes`` driver; each worker bootstraps through the
    parallel/multihost.py seam) must encode bit-exactly on the hybrid
    DCN x ICI mesh, and the host-loss leg (emulated 2-host topology,
    ``down_host`` injection) must retire the host as ONE event — one
    shrink, zero per-chip breaker trips, zero host fallbacks, the
    fused-crc family still closed.  Counters land in the contract
    line's `multihost` key, first-and-always under the PR-6
    watchdog; None (with a stderr note) when the probe cannot run."""
    if _remaining() < 0:
        print("# multihost probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    timeout_s = float(os.environ.get(
        "CEPH_TPU_BENCH_MULTIHOST_PROBE_TIMEOUT", "180"))
    # arm the collective-trace recorder in the worker processes: the
    # meshbench driver inherits this env and forwards it, and its
    # cross-worker congruence verdict rides back in the report for
    # _spmd_probe to check against the static site map
    prev = os.environ.get("CEPH_TPU_COLLECTIVE_TRACE")
    os.environ["CEPH_TPU_COLLECTIVE_TRACE"] = "1"
    try:
        return _meshbench_subprocess(["--processes", "2", "--smoke"],
                                     timeout_s)
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_COLLECTIVE_TRACE", None)
        else:
            os.environ["CEPH_TPU_COLLECTIVE_TRACE"] = prev


def _spmd_probe(multihost_counters: Optional[dict]) -> Optional[dict]:
    """Pre-contract collective-safety cross-check: the static
    collective-site map (analysis/collective.py) must be non-empty,
    and the 2-process smoke leg's runtime-observed collective trace
    (recorded by the multihost probe's workers) must be a subset of
    it with per-process order congruence — runtime ⊆ static, the
    same discipline as the lockdep and interleave checks."""
    if _remaining() < 0:
        print("# spmd probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    try:
        import ceph_tpu
        from ceph_tpu.analysis.collective import collective_site_map
        from ceph_tpu.analysis.core import build_project

        pkg = os.path.dirname(os.path.abspath(ceph_tpu.__file__))
        smap = collective_site_map(build_project([pkg]))
        out: dict = {
            "static_sites": len({(v["qualname"], k[0])
                                 for k, v in smap.items()}),
            "static_lines": len(smap),
            "runtime_sites": None,
            "runtime_subset_static": None,
            "order_congruent": None,
        }
        trace = None
        for row in (multihost_counters or {}).get(
                "process_sweep", []):
            if isinstance(row, dict) and \
                    row.get("spmd_trace") is not None:
                trace = row["spmd_trace"]
                out["order_congruent"] = row.get(
                    "spmd_order_congruent")
                break
        if trace is not None:
            pkg_sites = {(p, ln) for p, ln, *_ in trace
                         if p.startswith("ceph_tpu/")}
            out["runtime_sites"] = len(pkg_sites)
            out["runtime_subset_static"] = int(
                all(s in smap for s in pkg_sites))
        return out
    except Exception as exc:  # pragma: no cover - probe must not
        print(f"# spmd probe failed: {exc!r}",   # block the contract
              file=sys.stderr)
        return None


def bench_multihost() -> dict:
    """Cross-host scale-out section: the meshbench ``--processes``
    sweep axis — real jax.distributed process groups at 1 -> 2 (env
    CEPH_TPU_BENCH_MULTIHOST_PROCESSES widens it on real pods),
    bit-exact at every count, GiB/s per leg — plus the host-loss
    shrink leg.  Budget-gated like every optional section."""
    timeout_s = float(os.environ.get(
        "CEPH_TPU_BENCH_MULTIHOST_SWEEP_TIMEOUT", "300"))
    counts = os.environ.get("CEPH_TPU_BENCH_MULTIHOST_PROCESSES",
                            "1,2")
    args = ["--processes", counts] + (["--smoke"] if _SMOKE else [])
    out = _meshbench_subprocess(args, timeout_s)
    return out or {}


def bench_mesh() -> dict:
    """Mesh scale-out sweep: the fused encode+crc workload at mesh
    sizes 1 -> 2 -> 4 -> 8 (capped at visible devices), GiB/s per
    size and the speedup over the single-chip leg, bit-exactness
    asserted at every size.  The MULTICHIP driver rounds run the
    same sweep via __graft_entry__.dryrun_multichip's JSON tail."""
    timeout_s = float(os.environ.get(
        "CEPH_TPU_BENCH_MESH_SWEEP_TIMEOUT", "300"))
    args = ["--sweep"] + (["--smoke"] if _SMOKE else [])
    out = _meshbench_subprocess(args, timeout_s)
    return out or {}


def bench_degraded() -> dict:
    """Degraded-mode throughput delta: the same batched EC encode with
    the breakers forced open (every dispatch refused -> bit-exact
    numpy host path) vs the healthy device path — what a wedged
    accelerator actually costs while the breaker holds it out of the
    hot path."""
    from ceph_tpu.common import circuit
    from ceph_tpu.ec import dispatch as ec_dispatch
    from ceph_tpu.models import reed_solomon as rs

    k, m = 8, 3
    chunk = 4096 if _SMOKE else 256 * 1024
    batch = 2 if _SMOKE else 16
    mat = rs.reed_sol_van_matrix(k, m)
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    nbytes = batch * k * chunk

    def best_gibs(iters: int = 3) -> float:
        best = float("inf")
        ec_dispatch.gf_matmul(mat, data, use_tpu=True)  # warm/compile
        for _ in range(iters):
            t0 = time.perf_counter()
            ec_dispatch.gf_matmul(mat, data, use_tpu=True)
            best = min(best, time.perf_counter() - t0)
        return nbytes / best / (1 << 30)

    circuit.reset_all()
    device_gibs = best_gibs()
    circuit.force_open_all(duration=3600.0)
    try:
        host_gibs = best_gibs()
        fallbacks = circuit.breaker("ec-encode").stats()["fallbacks"]
    finally:
        circuit.reset_all()
    return {
        "degraded_device_gibs": device_gibs,
        "degraded_host_gibs": host_gibs,
        "degraded_delta_pct": round(
            (host_gibs - device_gibs) / device_gibs * 100.0, 2)
        if device_gibs else None,
        "degraded_fallbacks": fallbacks,
    }


def bench_repair() -> dict:
    """Repair-bandwidth-optimal recovery end to end: a live MSR
    (k=4 m=3 d=6) pool loses one OSD; the repair-aware recovery
    engine rebuilds each lost chunk from d beta-fragments (d/alpha =
    2 chunks of payload per rebuilt chunk vs the classic k-read's 4),
    then the same scenario runs with CEPH_TPU_MSR_REPAIR=0 for the
    classic k-read baseline.  Reports bytes-read-per-repaired-byte
    for both legs, the recovery wall clock, and the recover_read /
    recover_decode stage histograms the daemons recorded."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    n_objs = 4 if _SMOKE else 12
    osize = (16 << 10) if _SMOKE else (192 << 10)
    profile = {"plugin": "ec_msr", "k": "4", "m": "3", "d": "6",
               "crush-failure-domain": "osd"}

    async def leg() -> dict:
        cluster = Cluster(num_osds=9)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("msr", profile=profile,
                                                pg_num=8)
            io = cluster.client.open_ioctx("msr")
            rng = np.random.default_rng(0xD6)
            payloads = {
                f"o{i}": rng.integers(0, 256, osize + 31 * i,
                                      dtype=np.uint8).tobytes()
                for i in range(n_objs)}
            for oid, b in payloads.items():
                await io.write_full(oid, b)
            await cluster.kill_osd(0)
            await cluster.wait_for_osd_down(0)
            t0 = time.monotonic()
            await cluster.client.mon_command(
                {"prefix": "osd out", "osd": 0})
            await cluster.wait_for_clean(120)
            wall = time.monotonic() - t0
            for oid, b in payloads.items():
                assert await io.read(oid) == b, f"{oid} corrupt"
            perf = {key: sum(o.perf[key]
                             for o in cluster.osds.values())
                    for key in ("recovery_bytes_read",
                                "recovery_bytes_repaired",
                                "repair_objects", "repair_fragments",
                                "repair_fallbacks")}
            stages: dict = {}
            for osd in cluster.osds.values():
                for st, row in osd.tracer.stage_perf().items():
                    if st not in ("recover_read", "recover_decode"):
                        continue
                    agg = stages.setdefault(
                        st, {"count": 0, "sum_s": 0.0, "p99_ms": 0.0})
                    agg["count"] += row["count"]
                    agg["sum_s"] += row["self_seconds"].get("sum", 0.0)
                    agg["p99_ms"] = max(agg["p99_ms"], row["p99_ms"])
            return {"wall_s": wall, "perf": perf, "stages": stages}
        finally:
            await cluster.stop()

    def bytes_ratio(leg_out: dict) -> Optional[float]:
        made = leg_out["perf"]["recovery_bytes_repaired"]
        return round(leg_out["perf"]["recovery_bytes_read"] / made, 3) \
            if made else None

    # each leg runs twice: the first pays the one-time XLA traces of
    # the repair/decode plans (plan memoization is process-global and
    # the re-run's geometry matches exactly), the second is the
    # steady-state measurement — what a long-lived OSD actually sees
    on_cold = asyncio.run(leg())
    on = asyncio.run(leg())
    saved = os.environ.get("CEPH_TPU_MSR_REPAIR")
    os.environ["CEPH_TPU_MSR_REPAIR"] = "0"
    try:
        off_cold = asyncio.run(leg())
        off = asyncio.run(leg())
    finally:
        if saved is None:
            os.environ.pop("CEPH_TPU_MSR_REPAIR", None)
        else:
            os.environ["CEPH_TPU_MSR_REPAIR"] = saved
    r_on, r_off = bytes_ratio(on), bytes_ratio(off)
    return {
        "repair_bytes_per_repaired_byte": r_on,
        "repair_kread_bytes_per_repaired_byte": r_off,
        "repair_vs_kread_bytes": round(r_on / r_off, 3)
        if r_on and r_off else None,
        "repair_objects": on["perf"]["repair_objects"],
        "repair_fragments": on["perf"]["repair_fragments"],
        "repair_fallbacks": on["perf"]["repair_fallbacks"],
        "repair_recovery_wall_s": round(on["wall_s"], 3),
        "repair_kread_recovery_wall_s": round(off["wall_s"], 3),
        "repair_recovery_cold_wall_s": round(on_cold["wall_s"], 3),
        "repair_kread_recovery_cold_wall_s": round(
            off_cold["wall_s"], 3),
        "repair_stages": on["stages"],
        "repair_kread_stages": off["stages"],
    }


def _probe_on_daemon_thread(name: str, body, timeout_env: str,
                            default_timeout: str) -> Optional[dict]:
    """Run a pre-contract probe body on a DAEMON thread under a hard
    timeout — not a ThreadPoolExecutor: executor workers are
    non-daemon and joined at interpreter exit, so a wedged dispatch
    (or filesystem) would hang the whole bench after the contract
    line.  Returns the body's dict, or None (with a stderr note) when
    the probe is over budget, wedges past the timeout, or fails."""
    if _remaining() < 0:
        print(f"# {name} probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(timeout_env, default_timeout))
    try:
        import threading

        box: dict = {}

        def runner():
            try:
                box["out"] = body()
            except BaseException as e:  # surfaced below
                box["err"] = e

        t = threading.Thread(target=runner, daemon=True,
                             name=f"{name}-probe")
        t.start()
        t.join(probe_timeout)
        if t.is_alive():
            print(f"# {name} probe timed out (wedged?)",
                  file=sys.stderr)
            return None
        if "err" in box:
            raise box["err"]
        return box.get("out")
    except Exception as e:
        print(f"# {name} probe failed: {e!r}", file=sys.stderr)
        return None


def _tier_probe() -> Optional[dict]:
    """Pre-contract probe of the hot-set/read-tier subsystem: the
    device-batched bloom positions must match the host rjenkins oracle
    bit-exactly, and a zipfian stream through the TierAgent must
    record / promote / hit / evict.  Counters land in the contract
    line; None (with a stderr note) when the probe cannot run.

    Contract-first discipline (same as _service_probe): skipped when
    the wall-clock budget is spent, and the body — which includes a
    device dispatch — runs on a daemon thread under a hard timeout so
    a wedged tunnel cannot park the bench past the contract line."""
    return _probe_on_daemon_thread(
        "tier", _tier_probe_body,
        "CEPH_TPU_BENCH_TIER_PROBE_TIMEOUT", "60")


def _tier_probe_body() -> dict:
    """The probe proper; failures propagate to the runner thread's
    capture in _tier_probe — one reporting layer, like
    _service_probe."""
    from ceph_tpu.osd import hitset as hm
    from ceph_tpu.osd.tier import TierAgent
    from ceph_tpu.tools.rados import zipf_indices

    hashes = np.array([hm.hash_oid(f"probe_{i}")
                       for i in range(256)], dtype=np.uint32)
    nbits, nhash = hm.bloom_geometry(1024, 0.05)
    host = hm.bloom_positions(hashes, nbits, nhash)
    # 0 = no jax, the device lane never ran (positions_for would
    # silently fall back to the same host math being oracled)
    device_bitexact = 0
    if hm.HAVE_JAX:
        dev = hm.positions_for(hashes, nbits, nhash, device=True)
        assert np.array_equal(host, dev), "device/host bloom mismatch"
        device_bitexact = 1

    agent = TierAgent("bench-probe", {
        "osd_tier_enable": True,
        "osd_tier_promote_min_recency": 2,
        "osd_tier_cache_bytes": 8 << 10})
    payload = b"\xab" * 1024
    for i in zipf_indices(1.2, 32, 512, seed=7):
        oid = f"obj_{int(i)}"
        hits = agent.note_read("pg", oid)
        if agent.lookup("pg", oid) is not None:
            continue
        if agent.wants_promote("pg", oid, hits) and \
                agent.begin_promote("pg", oid):
            agent.end_promote("pg", oid, payload)
    c = agent.perf
    out = {key: c.get(key) for key in
           ("records", "hit", "miss", "promote", "evict")}
    out["device_bitexact"] = device_bitexact
    return out


def _repair_probe() -> Optional[dict]:
    """Pre-contract probe of the product-matrix MSR regenerating
    codec (ec/msr.py): every single-erasure pattern of a k=4 m=3 d=6
    profile must rebuild bit-exact from d beta-fragments, and the
    fragment bytes must land exactly on the MSR bound (d/alpha per
    chunk — half the classic k-read here).  Counters land in the
    contract line's repair key; None (with a stderr note) when the
    probe cannot run.

    Contract-first discipline (same as _tier_probe): skipped when the
    wall-clock budget is spent, and the body — whose matmuls may ride
    a device plan — runs on a daemon thread under a hard timeout."""
    return _probe_on_daemon_thread(
        "repair", _repair_probe_body,
        "CEPH_TPU_BENCH_REPAIR_PROBE_TIMEOUT", "60")


def _repair_probe_body() -> dict:
    from ceph_tpu.ec.registry import create_erasure_code

    k, m, d = 4, 3, 6
    n = k + m
    codec = create_erasure_code({"plugin": "ec_msr", "k": str(k),
                                 "m": str(m), "d": str(d)})
    alpha = codec.get_sub_chunk_count()
    rng = np.random.default_rng(0x4E7)
    data = rng.integers(0, 256, (1 << 14) if _SMOKE else (1 << 18),
                        dtype=np.uint8).tobytes()
    enc = codec.encode(range(n), data)
    chunks = {i: bytes(enc[i]) for i in range(n)}
    frag_bytes = kread_bytes = patterns = 0
    for lost in range(n):
        helpers = codec.minimum_to_repair(
            lost, [i for i in range(n) if i != lost])
        frags = {h: codec.repair_project(lost, chunks[h])
                 for h in helpers}
        assert codec.repair(lost, frags) == chunks[lost], \
            f"repair mismatch for shard {lost}"
        frag_bytes += sum(len(f) for f in frags.values())
        kread_bytes += k * len(chunks[lost])
        patterns += 1
    return {
        "patterns_bitexact": patterns,
        "k": k, "m": m, "d": d, "alpha": alpha,
        "bytes_ratio_vs_kread": round(frag_bytes / kread_bytes, 4),
    }


def _hedge_probe() -> Optional[dict]:
    """Pre-contract probe of the hedged-read scheduler (osd/hedge.py):
    six simulated sub-read peers, two of them 1 s stragglers, must
    complete a need=4 hedged gather from the first four DISTINCT
    arrivals — the stragglers' flights recruit the spare via the
    p95-EWMA hedge timer, then get cancelled AND awaited (no leaked
    tasks).  Counters land in the contract line's `tail` key; None
    (with a stderr note) when the probe cannot run."""
    if _remaining() < 0:
        print("# hedge probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_HEDGE_PROBE_TIMEOUT", "30"))
    try:
        import asyncio

        from ceph_tpu.osd.hedge import HedgeTracker

        async def run() -> dict:
            tracker = HedgeTracker("bench-probe", {
                "osd_hedge_delta": 1,
                "osd_hedge_rtt_prior_ms": 2.0,
                "osd_hedge_delay_floor_ms": 5.0,
            })
            delays = {0: 0.001, 1: 0.001, 2: 0.001,
                      3: 1.0, 4: 1.0, 5: 0.001}

            async def sub(shard: int) -> tuple:
                await asyncio.sleep(delays[shard])
                return ([(shard, bytes([shard]), {})], True)

            jobs = [(o, (lambda s=o: sub(s))) for o in range(6)]

            def sufficient(results) -> bool:
                return len({c[0] for sub_, _ok in results
                            for c in sub_}) >= 4

            t0 = time.perf_counter()
            results, _ran_all = await tracker.gather(
                jobs, need=4, sufficient=sufficient,
                failed=lambda r: not r[0])
            dt = time.perf_counter() - t0
            # drain leak check: nothing the gather spawned survives it
            leaked = [t for t in asyncio.all_tasks()
                      if t is not asyncio.current_task()
                      and t.get_name().startswith("hedge:")
                      and not t.done()]
            distinct = {c[0] for sub_, _ok in results for c in sub_}
            return {
                "completed_shards": len(distinct),
                "first_k_ms": round(dt * 1e3, 3),
                "straggler_avoided": int(dt < 0.5),
                "hedges_fired": tracker.counters["hedges_fired"],
                "hedge_wins": tracker.counters["hedge_wins"],
                "cancelled_subreads":
                    tracker.counters["cancelled_subreads"],
                "leaked_tasks": len(leaked),
            }

        return asyncio.run(asyncio.wait_for(run(), probe_timeout))
    except Exception as e:
        print(f"# hedge probe failed: {e!r}", file=sys.stderr)
        return None


def _compute_probe() -> Optional[dict]:
    """Pre-contract probe of the coded-compute subsystem
    (ceph_tpu/compute): (1) tiny scan bit-exact — every registered
    LINEAR kernel evaluated on a parity-including k-subset of one
    object's coded shards must result-domain-decode to exactly the
    host reference on the logical bytes; (2) the straggler leg — a
    need=k hedged sub-compute gather with one 1 s straggler completes
    from the first k shard-results, the straggler cancelled and
    awaited.  Counters land in the contract line's `compute` key;
    None (with a stderr note) when the probe cannot run."""
    if _remaining() < 0:
        print("# compute probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_COMPUTE_PROBE_TIMEOUT", "60"))
    try:
        import asyncio

        from ceph_tpu import compute as compute_mod
        from ceph_tpu.ec.registry import create_erasure_code
        from ceph_tpu.osd import ec_util
        from ceph_tpu.osd.hedge import HedgeTracker

        k, m = 2, 2
        codec = create_erasure_code({
            "plugin": "ec_jax", "technique": "reed_sol_van",
            "k": str(k), "m": str(m)})
        unit = codec.get_chunk_size(k * 4096)
        sinfo = ec_util.StripeInfo(k, k * unit)
        rng = np.random.default_rng(41)
        data = rng.integers(0, 256, sinfo.get_stripe_width() + 97,
                            dtype=np.uint8).tobytes()
        padded = data + bytes(-len(data) % sinfo.get_stripe_width())
        shards = ec_util.encode(sinfo, codec, padded,
                                range(codec.get_chunk_count()))

        def result_decode(kern, chosen) -> bytes:
            rsinfo = ec_util.StripeInfo(k, k * kern.lanes)
            dec = bytes(ec_util.decode(rsinfo, codec, chosen))
            return bytes(kern.combine(
                [dec[i * kern.lanes:(i + 1) * kern.lanes]
                 for i in range(k)]))

        linear = compute_mod.linear_kernels()
        bitexact = 1
        chosen_ids = (1, k + m - 1)  # data+parity mix
        for kern in linear.values():
            ref = bytes(kern.reference(
                data, {}, k=k, chunk=sinfo.get_chunk_size()))
            res = compute_mod.shard_eval_batch(
                kern, [shards[i] for i in chosen_ids], {})
            got = result_decode(
                kern, {i: r for i, r in zip(chosen_ids, res)})
            if got != ref:
                bitexact = 0

        async def straggler_leg() -> dict:
            kern = next(iter(linear.values()))
            tracker = HedgeTracker("bench-compute-probe", {
                "osd_hedge_delta": 1,
                "osd_hedge_rtt_prior_ms": 2.0,
                "osd_hedge_delay_floor_ms": 5.0,
            })
            delays = {0: 0.001, 1: 0.001, 2: 1.0, 3: 0.001}

            async def sub(shard: int) -> tuple:
                await asyncio.sleep(delays[shard])
                res = compute_mod.shard_eval_batch(
                    kern, [shards[shard]], {})
                return shard, True, res[0]

            jobs = [(o, (lambda s=o: sub(s)))
                    for o in range(k + m)]

            def sufficient(results) -> bool:
                return len({r[0] for r in results if r[1]}) >= k

            t0 = time.perf_counter()
            results, _ran_all = await tracker.gather(
                jobs, need=k, sufficient=sufficient,
                failed=lambda r: not r[1], label="subcompute")
            dt = time.perf_counter() - t0
            ref = bytes(kern.reference(
                data, {}, k=k, chunk=sinfo.get_chunk_size()))
            first_k = {r[0]: r[2] for r in results if r[1]}
            chosen = dict(list(first_k.items())[:k]) \
                if len(first_k) >= k else None
            ok = chosen is not None and \
                result_decode(kern, chosen) == ref
            return {
                "first_k_ms": round(dt * 1e3, 3),
                "straggler_avoided": int(dt < 0.5),
                "first_k_bitexact": int(ok),
                "cancelled_subcomputes":
                    tracker.counters["cancelled_subreads"],
            }

        leg = asyncio.run(asyncio.wait_for(straggler_leg(),
                                           probe_timeout))
        return {
            "bitexact": bitexact,
            "linear_kernels": len(linear),
            "kernels": len(compute_mod.registered_kernels()),
            **leg,
        }
    except Exception as e:
        print(f"# compute probe failed: {e!r}", file=sys.stderr)
        return None


def _inference_probe() -> Optional[dict]:
    """Pre-contract probe of coded inference serving
    (ceph_tpu/inference): (1) the exact combine over all k data
    contributions is BIT-identical to the host oracle
    (model.exact_forward); (2) every single-data-shard-loss pattern
    serves from the Fisher-fused substitute streams with true
    relative error <= the structural estimate <= the budget; (3) the
    straggler leg — a hedged sub-infer gather with one 1 s straggler
    completes from the first structurally-sufficient arrival set,
    combines within budget, and cancels the straggler.  Counters land
    in the contract line's `inference` key; None (with a stderr note)
    when the probe cannot run."""
    if _remaining() < 0:
        print("# inference probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_INFER_PROBE_TIMEOUT", "60"))
    try:
        import asyncio

        from ceph_tpu.inference import fisher, model, registry
        from ceph_tpu.osd.hedge import HedgeTracker

        k, m, chunk, budget, nq = 3, 2, 1024, 0.05, 16
        spec, blobs = registry.build(
            "bench-model", "linear",
            registry.make_model("linear", 32, 48, seed=11),
            k, m, chunk)
        data = blobs[registry.params_oid("bench-model")]
        streams = model.object_streams(spec, data)
        q = np.random.default_rng(17).standard_normal(
            (nq, 32)).astype(np.float32)
        exact = model.exact_forward(spec, data, q)
        eref = float(np.linalg.norm(exact)) or 1.0
        parts = {i: model.shard_forward(spec, streams[i], q)
                 for i in range(k)}
        fused = {j: model.shard_forward(spec, streams[k + j], q)
                 for j in range(m)}
        # all-data combine funnels through the same fixed op order as
        # the oracle: bit-identical, not merely close
        all_data = fisher.combine(spec, parts, {}, q, budget)
        bitexact = int(all_data is not None and
                       all_data[0].tobytes() == exact.tobytes())
        patterns, within = 0, 1
        max_rel, max_est = 0.0, 0.0
        for drop in range(k):
            dp = {i: parts[i] for i in range(k) if i != drop}
            res = fisher.combine(spec, dp, fused, q, budget)
            patterns += 1
            if res is None:
                within = 0
                continue
            scores, est, _sub = res
            rel = float(np.linalg.norm(scores - exact)) / eref
            max_rel, max_est = max(max_rel, rel), max(max_est, est)
            if not (rel <= est and fisher.check_budget(est, budget)):
                within = 0

        async def straggler_leg() -> dict:
            tracker = HedgeTracker("bench-infer-probe", {
                "osd_hedge_delta": 1,
                "osd_hedge_rtt_prior_ms": 2.0,
                "osd_hedge_delay_floor_ms": 5.0,
            })
            delays = {i: 0.001 for i in range(k + m)}
            delays[1] = 1.0  # one slow data-stream holder
            qscale = fisher.query_scale(q)

            async def sub(idx: int) -> tuple:
                await asyncio.sleep(delays[idx])
                return idx, True, model.shard_forward(
                    spec, streams[idx], q)

            jobs = [(i, (lambda s=i: sub(s))) for i in range(k + m)]

            def sufficient(results) -> bool:
                got = {r[0] for r in results if r[1]}
                est = fisher.structural_error(
                    spec, sorted(i for i in got if i < k),
                    sorted(i - k for i in got if i >= k), qscale)
                return est is not None and \
                    fisher.check_budget(est, budget)

            t0 = time.perf_counter()
            results, _ran_all = await tracker.gather(
                jobs, need=k, sufficient=sufficient,
                failed=lambda r: not r[1], label="subinfer")
            dt = time.perf_counter() - t0
            got = {r[0]: r[2] for r in results if r[1]}
            res = fisher.combine(
                spec, {i: v for i, v in got.items() if i < k},
                {i - k: v for i, v in got.items() if i >= k},
                q, budget)
            ok = res is not None and \
                float(np.linalg.norm(res[0] - exact)) / eref <= budget
            return {
                "first_sufficient_ms": round(dt * 1e3, 3),
                "straggler_avoided": int(dt < 0.5),
                "straggler_within_budget": int(ok),
                "substituted_streams": res[2] if res else -1,
                "cancelled_subinfers":
                    tracker.counters["cancelled_subreads"],
            }

        leg = asyncio.run(asyncio.wait_for(straggler_leg(),
                                           probe_timeout))
        return {
            "bitexact": bitexact,
            "patterns": patterns,
            "within_budget": within,
            "max_rel_err": round(max_rel, 9),
            "max_est_error": round(max_est, 9),
            "budget": budget,
            **leg,
        }
    except Exception as e:
        print(f"# inference probe failed: {e!r}", file=sys.stderr)
        return None


def _xsched_probe() -> Optional[dict]:
    """Pre-contract probe of the XOR-schedule codec compiler
    (ec/xsched.py): the bitmatrix trio's encode matrices, two decode
    submatrices and a GF(2^8) cauchy bit expansion compile into
    schedules that execute BIT-EXACTLY against the naive row-walk
    oracle; the memo serves repeat compiles from cache; and the best
    measured XOR-count reduction clears the >=25% acceptance bar
    (decode inverses and GF expansions are where the CSE bites —
    encode matrices of the minimal-density codes reduce less, by
    design).  A native leg lowers one schedule to the fused C++ tape
    executor and asserts bit-parity against the host walk, with the
    tape-cache and native-exec counters carried alongside — so the
    contract shows the kill-switch seam (native vs execute_host)
    exercised every round.  Counters land in the contract line's
    `xsched` key; None (with a stderr note) when the probe cannot
    run."""
    if _remaining() < 0:
        print("# xsched probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    try:
        from ceph_tpu.ec import xsched
        from ceph_tpu.models import bitmatrix as bmx
        from ceph_tpu.models import reed_solomon as rs
        from ceph_tpu.ops import gf as gf_ops

        lib = bmx.liberation_bitmatrix(4, 7)
        l8 = bmx.liber8tion_bitmatrix(4)
        cases = {
            "liberation": lib,
            "blaum_roth": bmx.blaum_roth_bitmatrix(4, 6),
            "liber8tion": l8,
            "liberation_decode": bmx.decode_bitmatrix(
                lib, 4, 7, (2, 3, 4, 5), (0, 1)),
            "liber8tion_decode": bmx.decode_bitmatrix(
                l8, 4, 8, (1, 2, 3, 4), (0, 5)),
            "cauchy_good_bits": gf_ops.gf_matrix_to_bits(
                rs.cauchy_good_matrix(4, 2)),
        }
        rng = np.random.default_rng(17)
        before = xsched.stats()
        bitexact = 1
        reductions = {}
        for name, bm in cases.items():
            sched = xsched.compile_matrix(bm)
            pk = rng.integers(0, 256, (2, bm.shape[1], 64),
                              dtype=np.uint8)
            want = xsched.naive_xor_matmul(bm, pk)
            out = np.zeros((2, bm.shape[0], 64), dtype=np.uint8)
            xsched.execute_host(
                sched, [pk[:, c, :] for c in range(bm.shape[1])],
                [out[:, r, :] for r in range(bm.shape[0])])
            if not np.array_equal(out, want):
                bitexact = 0
            reductions[name] = round(sched.reduction_pct, 1)
            xsched.compile_matrix(bm)        # the memo leg
        # native-executor leg: lower the liber8tion schedule to the
        # fused C++ op tape, run it on a packed multi-object arena,
        # and hold it bit-exact against the host walk — then repeat
        # through the execute() seam so the native-vs-host dispatch
        # counter moves too
        native_ok = 1 if xsched.native_available() else 0
        native_bitexact = None
        if native_ok:
            sched = xsched.compile_matrix(l8)
            prog = xsched.lower_program(sched)
            rb = 64
            arena = np.zeros((3, prog.n_regions, rb), dtype=np.uint8)
            pk = rng.integers(0, 256, (3, l8.shape[1], rb),
                              dtype=np.uint8)
            arena[:, :l8.shape[1], :] = pk
            xsched.execute_native(prog, arena)
            native_bitexact = int(np.array_equal(
                arena[:, prog.out_base:, :],
                xsched.naive_xor_matmul(l8, pk)))
            outs = [np.zeros(rb, dtype=np.uint8)
                    for _ in range(l8.shape[0])]
            tier = xsched.execute(
                sched, [np.ascontiguousarray(pk[0, c])
                        for c in range(l8.shape[1])], outs)
            if tier != "native" or not np.array_equal(
                    np.stack(outs),
                    xsched.naive_xor_matmul(l8, pk[:1])[0]):
                native_bitexact = 0
        after = xsched.stats()
        return {
            "bitexact": bitexact,
            "xor_reduction_pct": max(reductions.values()),
            "reductions": reductions,
            "schedules": after["compiled"] - before["compiled"],
            "cache_hits": after["cache_hits"] - before["cache_hits"],
            "xors_naive": after["xors_naive"] - before["xors_naive"],
            "xors_scheduled": after["xors_scheduled"]
            - before["xors_scheduled"],
            "native_available": native_ok,
            "native_bitexact": native_bitexact,
            "exec_native": after["exec_native"] - before["exec_native"],
            "tape_misses": after["tape_misses"] - before["tape_misses"],
            "tape_hits": after["tape_hits"] - before["tape_hits"],
        }
    except Exception as e:
        print(f"# xsched probe failed: {e!r}", file=sys.stderr)
        return None


def _trace_probe() -> Optional[dict]:
    """Pre-contract probe of the critical-path tracing layer.  Two
    halves: (1) the critical-path reducer reconstructs a hand-built
    span tree correctly — the longest hedged child owns the wait, the
    cancelled straggler is off the path; (2) the measured op-throughput
    delta of spans ON (sample rate 0 — the production bulk
    configuration) vs the CEPH_TPU_TRACE=0 kill switch, driven through
    a live loopback cluster so the per-op cost is the real pipeline,
    alternating phases on one cluster with min-of filtering.  Counters
    land in the contract line's `trace` key; None (with a stderr note)
    when the probe cannot run."""
    if _remaining() < 0:
        print("# trace probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_TRACE_PROBE_TIMEOUT", "90"))
    try:
        import asyncio

        from ceph_tpu.common import tracing

        # -- half 1: reducer sanity on a hand-built tree -------------
        mk = lambda sid, parent, name, t0, dur, **attrs: {  # noqa: E731
            "span_id": sid, "parent_id": parent, "name": name,
            "t0_us": t0, "duration_us": dur,
            "attrs": attrs or {}}
        tree = [
            mk("r", "", "osd_op obj", 0, 10_000),
            mk("q", "r", "queue.client", 0, 2_000),
            mk("a", "r", "subread osd.1", 2_000, 7_000),
            mk("b", "r", "subread osd.2", 2_000, 8_000,
               cancelled=True),
        ]
        cp = tracing.critical_path(tree)
        st = cp["stages"]
        cp_ok = int(cp["total_us"] == 10_000
                    and st.get("queue.client") == 2_000
                    and st.get("subread") == 7_000
                    and st.get("osd_op") == 1_000)

        # deterministic span-layer cost: the representative per-op
        # span shape (root + queue + encode_wait + 3 sub-op children +
        # reduce + stage histograms), microbenchmarked — the stable
        # numerator behind the noisier live A/B delta below
        # Tracer.enabled re-reads CEPH_TPU_TRACE per trace: force it ON
        # for the microbench (a bench launched with the kill switch
        # armed would otherwise time NULL_SPAN no-ops and report a
        # vacuous ~0% overhead_ratio_pct); half 2 below forces the env
        # per phase and the shared finally restores the caller's value
        prev = os.environ.get("CEPH_TPU_TRACE")
        os.environ["CEPH_TPU_TRACE"] = "1"
        try:
            tracer = tracing.Tracer("probe", sample_rate=0.0)
            tracer.record_stages({"warm": 1})  # one-time lazy import
            n_syn = 2000
            t0 = time.perf_counter()
            for _ in range(n_syn):
                root = tracer.start("osd_op obj")
                tok = tracing.current_span.set(root)
                for name in ("queue.client", "encode_wait",
                             "subread osd.0", "subread osd.1",
                             "subread osd.2"):
                    root.child(name).finish()
                tracing.current_span.reset(tok)
                tracer.finish(root)
                tracer.record_stages(
                    tracing.critical_path_spans(root)["stages"])
            span_cost_us = (time.perf_counter() - t0) / n_syn * 1e6
        finally:
            if prev is None:
                os.environ.pop("CEPH_TPU_TRACE", None)
            else:
                os.environ["CEPH_TPU_TRACE"] = prev

        # -- half 2: overhead of spans-on (rate 0) vs kill switch ----
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tests"))
        from cluster_helpers import Cluster

        n_ops = 30 if _SMOKE else 80
        payload = bytes(bytearray(range(256))) * 128  # 32 KiB
        profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
                   "k": "2", "m": "1", "crush-failure-domain": "osd"}

        async def run() -> dict:
            cluster = Cluster(
                num_osds=3, osds_per_host=3,
                osd_config={"osd_trace_sample_rate": 0.0})
            await cluster.start()
            try:
                # EC pool: the product data path (encode service,
                # hedged sub-reads, fused plans) — the op cost the
                # span layer is amortized against in production
                await cluster.client.create_ec_pool(
                    "traceprobe", profile=profile, pg_num=4)
                io = cluster.client.open_ioctx("traceprobe")

                async def phase() -> float:
                    t0 = time.perf_counter()
                    for i in range(n_ops):
                        await io.write_full(f"o{i % 8}", payload)
                        await io.read(f"o{i % 8}")
                    return time.perf_counter() - t0

                await phase()  # warm: placement, plans, stores
                times = {"on": [], "off": []}
                for mode in ("off", "on", "off", "on", "off", "on"):
                    os.environ["CEPH_TPU_TRACE"] = \
                        "0" if mode == "off" else "1"
                    times[mode].append(await phase())
                stages = set()
                samples = 0
                for osd in cluster.osds.values():
                    stages.update(osd.tracer.stage_hist)
                    samples += osd.tracer.counters["stage_samples"]
                # min-of-3 per mode: alternating phases on ONE live
                # cluster, minima filter scheduler/GC hiccups
                t_on, t_off = min(times["on"]), min(times["off"])
                op_cost_us = t_off / (2 * n_ops) * 1e6
                return {
                    "ops_per_phase": 2 * n_ops,
                    # live A/B delta (noisy on shared hardware) ...
                    "overhead_pct": round(
                        (t_on - t_off) / t_off * 100.0, 2),
                    # ... and the stable decomposition: span-layer
                    # cost over the real per-op cost
                    "op_cost_us": round(op_cost_us, 1),
                    "overhead_ratio_pct": round(
                        span_cost_us / op_cost_us * 100.0, 2),
                    "stages_seen": len(stages),
                    "stage_samples": samples,
                }
            finally:
                await cluster.stop()

        prev = os.environ.get("CEPH_TPU_TRACE")
        try:
            out = asyncio.run(asyncio.wait_for(run(), probe_timeout))
        finally:
            if prev is None:
                os.environ.pop("CEPH_TPU_TRACE", None)
            else:
                os.environ["CEPH_TPU_TRACE"] = prev
        out["span_cost_us"] = round(span_cost_us, 2)
        out["cp_ok"] = cp_ok
        return out
    except Exception as e:
        print(f"# trace probe failed: {e!r}", file=sys.stderr)
        return None


def bench_trace() -> dict:
    """Per-stage latency decomposition under load: concurrent mixed
    R/W clients against a live EC cluster with tracing on, then the
    OSDs' per-stage critical-path histograms roll up (element-wise
    LatencyHistogram merge, the loadgen harness's streaming
    percentiles) into stage p50/p99 self-times — the decomposition
    ROADMAP items 2-4 are judged by."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    n_clients = 4 if _SMOKE else 8
    ops_each = 16 if _SMOKE else 48
    osize = 16 << 10
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "2", "crush-failure-domain": "osd"}

    async def run() -> dict:
        cluster = Cluster(num_osds=5, osds_per_host=5)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "tracebench", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("tracebench")

            async def worker(c: int) -> None:
                data = b"%d" % c * (osize // 2)
                for i in range(ops_each):
                    oid = f"c{c}-o{i % 6}"
                    await io.write_full(oid, data)
                    await io.read(oid)

            await asyncio.gather(*(worker(c)
                                   for c in range(n_clients)))
            from ceph_tpu.loadgen.stats import LatencyHistogram

            merged: dict = {}
            for osd in cluster.osds.values():
                for stage, h in osd.tracer.stage_hist.items():
                    agg = merged.setdefault(stage, LatencyHistogram())
                    agg.merge(h)
            out = {}
            for stage, h in sorted(merged.items()):
                p50, p99 = h.percentile(0.5), h.percentile(0.99)
                out[stage] = {
                    "count": h.count,
                    "p50_ms": round(p50 * 1e3, 3) if p50 else 0.0,
                    "p99_ms": round(p99 * 1e3, 3) if p99 else 0.0,
                }
            return {"trace_stage_summary": out}
        finally:
            await cluster.stop()

    return asyncio.run(run())


def bench_tail() -> dict:
    """Tail-latency leg through a live cluster: EC reads with ONE
    injected slow OSD (ms_inject_internal_delays on that daemon's
    messenger), hedging on vs off.  Reads target objects whose PG
    primary is NOT the slow OSD, so the slow peer sits on the
    sub-read fan-out path — exactly the straggler the hedged first-k
    gather is built to cut out.  Reports p50/p95/p99 per mode, the
    p99 improvement multiple, byte-equality across modes, and the
    summed hedge counters."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    n_objs = 8 if _SMOKE else 24
    osize = 8 << 10 if _SMOKE else 32 << 10
    n_reads = 24 if _SMOKE else 96
    delay = 0.05 if _SMOKE else 0.2
    payloads = [np.random.default_rng(500 + i).integers(
        0, 256, osize, dtype=np.uint8).tobytes()
        for i in range(n_objs)]
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "2", "crush-failure-domain": "osd"}

    def pct(lat, q):
        lat = sorted(lat)
        return lat[min(len(lat) - 1, int(q * (len(lat) - 1) + 0.5))]

    async def run_mode():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "tail", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("tail")
            for i in range(n_objs):
                await io.write_full(f"t{i}", payloads[i])
            # slow OSD choice is deterministic across modes (same
            # seeds -> same CRUSH placement): the one that is primary
            # for the FEWEST of our objects, so most reads exercise it
            # as a sub-read peer, not as the op target
            primaries: dict = {}
            acting_of: dict = {}
            for i in range(n_objs):
                pg = io.object_pg(f"t{i}")
                acting, p = cluster.mon.osdmap.pg_to_acting_osds(pg)
                primaries[i] = p
                acting_of[i] = acting
            counts = {o: 0 for o in cluster.osds}
            for p in primaries.values():
                counts[p] = counts.get(p, 0) + 1
            slow = min(sorted(counts), key=lambda o: counts[o])
            targets = [i for i in range(n_objs)
                       if primaries[i] != slow
                       and slow in acting_of[i]]
            if not targets:
                targets = [i for i in range(n_objs)
                           if primaries[i] != slow]
            cluster.osds[slow].msgr.inject_internal_delays = delay
            # warm pass: the primaries learn the slow peer's EWMA
            for i in targets:
                await io.read(f"t{i}")
            lats = []
            datas = {}
            for r in range(n_reads):
                i = targets[r % len(targets)]
                t0 = time.perf_counter()
                datas[i] = await io.read(f"t{i}")
                lats.append(time.perf_counter() - t0)
            ok = all(bytes(d) == payloads[i]
                     for i, d in datas.items())
            hedge: dict = {}
            for osd in cluster.osds.values():
                for key, v in osd.hedge.counters.items():
                    hedge[key] = hedge.get(key, 0) + v
            return lats, ok, hedge
        finally:
            await cluster.stop()

    prev = os.environ.get("CEPH_TPU_HEDGE")
    prev_tier = os.environ.get("CEPH_TPU_TIER")
    try:
        # the read tier (PR 4) would serve hot repeats from memory and
        # measure cache residency instead of the sub-read tail — both
        # modes run tier-off so the delta isolates the hedged gather
        os.environ["CEPH_TPU_TIER"] = "0"
        os.environ["CEPH_TPU_HEDGE"] = "1"
        lat_on, ok_on, hedge_counters = asyncio.run(run_mode())
        os.environ["CEPH_TPU_HEDGE"] = "0"
        lat_off, ok_off, _h = asyncio.run(run_mode())
    finally:
        for name, val in (("CEPH_TPU_HEDGE", prev),
                          ("CEPH_TPU_TIER", prev_tier)):
            if val is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = val
    out = {}
    for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        out[f"tail_read_{name}_hedged_ms"] = round(
            pct(lat_on, q) * 1e3, 3)
        out[f"tail_read_{name}_unhedged_ms"] = round(
            pct(lat_off, q) * 1e3, 3)
    out["tail_p99_improvement_x"] = round(
        pct(lat_off, 0.99) / max(pct(lat_on, 0.99), 1e-9), 2)
    out["tail_bytes_identical"] = bool(ok_on and ok_off)
    out["tail_hedge_counters"] = hedge_counters
    return out


def bench_compute() -> dict:
    """Coded-compute scan leg through a live cluster: scan N objects
    with a linear kernel as (1) coded-compute pushdown and (2)
    client-side read-then-compute (CEPH_TPU_COMPUTE=0), reporting
    wall-clock per mode, the speedup multiple, bytes moved per mode
    (sub-read payload bytes vs lane-width result bytes), the
    per-stage trace decomposition of the scan, and the straggler leg
    — the same pushdown scan with one injected slow OSD, whose
    wall-clock must stay flat (hedged first-k sub-computes) while an
    unhedged read-then-compute pays the delay."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    n_objs = int(os.environ.get(
        "CEPH_TPU_BENCH_COMPUTE_OBJECTS",
        "32" if _SMOKE else "10000"))
    if not _SMOKE and _remaining() < 240:
        # a shrunken leg beats a skipped one when the clock runs low
        n_objs = min(n_objs, 2000)
    osize = 4096
    delay = 0.05 if _SMOKE else 0.25
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "2", "crush-failure-domain": "osd"}
    payload = np.random.default_rng(600).integers(
        0, 256, osize, dtype=np.uint8).tobytes()

    async def run() -> dict:
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 30.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "compute", profile=profile, pg_num=16)
            io = cluster.client.open_ioctx("compute")
            t0 = time.perf_counter()
            sem = asyncio.Semaphore(64)  # bounded: the op queue is

            async def put(i: int) -> None:
                async with sem:
                    await io.write_full(f"c{i}", payload)

            await asyncio.gather(*(put(i) for i in range(n_objs)))
            prefill_s = time.perf_counter() - t0
            oids = [f"c{i}" for i in range(n_objs)]

            def subread_bytes() -> int:
                return sum(o.perf["subread_bytes"]
                           for o in cluster.osds.values())

            def result_bytes() -> int:
                return sum(o.compute.perf()["result_bytes"]
                           for o in cluster.osds.values())

            # leg 1: pushdown scan
            sb0, rb0 = subread_bytes(), result_bytes()
            t0 = time.perf_counter()
            res_push, err = await io.compute("gf_fold", oids)
            push_s = time.perf_counter() - t0
            assert not err, err
            push_payload_bytes = subread_bytes() - sb0
            push_result_bytes = result_bytes() - rb0

            # leg 2: client-side read-then-compute
            os.environ["CEPH_TPU_COMPUTE"] = "0"
            try:
                sb0 = subread_bytes()
                t0 = time.perf_counter()
                res_read, err = await io.compute("gf_fold", oids)
                read_s = time.perf_counter() - t0
            finally:
                os.environ.pop("CEPH_TPU_COMPUTE", None)
            assert not err, err
            read_payload_bytes = subread_bytes() - sb0
            bitexact = all(bytes(res_push[o]) == bytes(res_read[o])
                           for o in oids)

            # leg 3: straggler — slow the least-primary OSD, rerun
            # the pushdown scan over objects it does not primary
            counts = {o: 0 for o in cluster.osds}
            acting_of = {}
            for oid in oids[:256]:
                pg = io.object_pg(oid)
                acting, p = cluster.mon.osdmap.pg_to_acting_osds(pg)
                acting_of[oid] = (acting, p)
                counts[p] = counts.get(p, 0) + 1
            slow = min(sorted(counts), key=lambda o: counts[o])
            targets = [oid for oid, (acting, p) in acting_of.items()
                       if p != slow and slow in acting] or \
                [oid for oid, (_a, p) in acting_of.items()
                 if p != slow]
            # baseline over the SAME targets (amortized plans, no
            # delay), then the slow-OSD leg: flat means the scan
            # pays wave overhead, never the injected delay per wave
            t0 = time.perf_counter()
            await io.compute("gf_fold", targets)
            base_s = time.perf_counter() - t0
            cluster.osds[slow].msgr.inject_internal_delays = delay
            t0 = time.perf_counter()
            res_slow, err = await io.compute("gf_fold", targets)
            slow_s = time.perf_counter() - t0
            cluster.osds[slow].msgr.inject_internal_delays = 0
            assert not err, err
            slow_ok = all(bytes(res_slow[o]) == bytes(res_push[o])
                          for o in targets)

            # per-stage decomposition of the scan (compute stages
            # only — the proof the win is attributable)
            stages = {}
            for osd in cluster.osds.values():
                for stage, row in osd.tracer.stage_perf().items():
                    if "compute" not in stage:
                        continue
                    agg = stages.setdefault(
                        stage, {"count": 0, "p99_ms": 0.0})
                    agg["count"] += row.get("count", 0)
                    agg["p99_ms"] = max(agg["p99_ms"],
                                        row.get("p99_ms", 0.0))
            hedged = sum(o.hedge.counters["hedged_gathers"]
                         for o in cluster.osds.values())
            return {
                "compute_objects": n_objs,
                "compute_prefill_s": round(prefill_s, 3),
                "compute_pushdown_s": round(push_s, 3),
                "compute_read_then_compute_s": round(read_s, 3),
                "compute_speedup_x": round(
                    read_s / max(push_s, 1e-9), 2),
                "compute_pushdown_payload_bytes": push_payload_bytes,
                "compute_pushdown_result_bytes": push_result_bytes,
                "compute_read_payload_bytes": read_payload_bytes,
                "compute_bytes_ratio": round(
                    read_payload_bytes
                    / max(push_payload_bytes + push_result_bytes, 1),
                    1),
                "compute_bitexact": int(bitexact),
                "compute_straggler_objects": len(targets),
                "compute_straggler_base_s": round(base_s, 3),
                "compute_straggler_scan_s": round(slow_s, 3),
                "compute_straggler_delay_s": delay,
                "compute_straggler_flat": int(
                    slow_s < max(2.0 * base_s,
                                 base_s + 2.0 * delay)),
                "compute_straggler_bitexact": int(slow_ok),
                "compute_hedged_gathers": hedged,
                "compute_stage_ms": {
                    k: {"count": v["count"],
                        "p99_ms": round(v["p99_ms"], 3)}
                    for k, v in sorted(stages.items())},
            }
        finally:
            await cluster.stop()

    return asyncio.run(run())


def bench_inference() -> dict:
    """Coded inference serving leg through a live cluster: a linear
    scorer stored Fisher-fused into an EC pool, queried (1) through
    the code (approximate serving allowed under the default budget),
    (2) exact through the code, and (3) client-side read-then-infer
    (CEPH_TPU_INFERENCE=0) — reporting wall-clock and sub-read bytes
    moved per mode, the approx-vs-exact accuracy delta against the
    budget, the kill-switch bit-parity, the per-stage infer trace
    decomposition, and the straggler leg: per-query p99 with one
    injected slow stream-holder OSD, coded serving vs the degraded
    read-then-infer baseline."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster
    from ceph_tpu.inference import registry
    from ceph_tpu.loadgen.stats import LatencyHistogram

    n_ops = int(os.environ.get("CEPH_TPU_BENCH_INFER_OPS",
                               "24" if _SMOKE else "200"))
    nq, dim, out = 16, 64, 256
    delay = 0.05 if _SMOKE else 0.25
    budget = 0.05
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "3", "m": "2", "crush-failure-domain": "osd"}

    async def run() -> dict:
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 30.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "infer", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("infer")
            spec = await io.store_model(
                "bench-model", "linear",
                registry.make_model("linear", dim, out, seed=23),
                m=1)
            rng = np.random.default_rng(29)
            batches = [rng.standard_normal((nq, dim)
                                           ).astype(np.float32)
                       for _ in range(n_ops)]
            await io.infer(spec, batches[0])  # warm plans/admission
            # client-visible wire cost per mode: read-then-infer
            # ships the WHOLE params object down every op; coded
            # serving ships the query batch up and the scores blob
            # back (result_bytes on the compute engine).  OSD-side
            # sub-read counters are useless here — the exact leg
            # promotes the params object into the hot tier and later
            # client reads skip the EC fan-out.
            params_bytes = len(await io.read(spec["params_oid"]))
            query_bytes = batches[0].nbytes

            def result_bytes() -> int:
                return sum(o.compute.perf()["result_bytes"]
                           for o in cluster.osds.values())

            async def sweep(exact: bool = False,
                            hist: Optional[LatencyHistogram] = None
                            ) -> tuple:
                t0 = time.perf_counter()
                results = []
                for qb in batches:
                    s0 = time.perf_counter()
                    results.append(await io.infer(spec, qb,
                                                  exact=exact))
                    if hist is not None:
                        hist.record(time.perf_counter() - s0)
                return time.perf_counter() - t0, results

            # leg 1: coded serving (approximate allowed)
            rb0 = result_bytes()
            coded_s, res_coded = await sweep()
            coded_bytes = (result_bytes() - rb0
                           + n_ops * query_bytes)
            # leg 2: exact through the code (full-decode fallback)
            exact_s, res_exact = await sweep(exact=True)
            # leg 3: kill switch — client-side read-then-infer
            os.environ["CEPH_TPU_INFERENCE"] = "0"
            try:
                read_s, res_read = await sweep()
                read_bytes = n_ops * params_bytes
            finally:
                os.environ.pop("CEPH_TPU_INFERENCE", None)
            parity = all(
                a["scores"].tobytes() == b["scores"].tobytes()
                for a, b in zip(res_exact, res_read))
            max_rel = max(
                float(np.linalg.norm(a["scores"] - b["scores"]) /
                      max(np.linalg.norm(b["scores"]), 1e-12))
                for a, b in zip(res_coded, res_exact))
            max_est = max(float(r["est_error"]) for r in res_coded)
            modes = {}
            for r in res_coded:
                modes[r["mode"]] = modes.get(r["mode"], 0) + 1

            # straggler leg: slow a non-primary holder of one of the
            # model's serving streams (acting[:k+m of the MODEL]);
            # the hedged sub-infer fan-out must keep coded p99 flat
            pg = io.object_pg(spec["params_oid"])
            acting, primary = cluster.mon.osdmap.pg_to_acting_osds(pg)
            nstreams = int(spec["k"]) + int(spec["m"])
            slow = next(o for o in acting[:nstreams]
                        if o != primary and o >= 0)
            base_h = LatencyHistogram()
            await sweep(hist=base_h)
            cluster.osds[slow].msgr.inject_internal_delays = delay
            try:
                slow_h = LatencyHistogram()
                _s, res_slow = await sweep(hist=slow_h)
                os.environ["CEPH_TPU_INFERENCE"] = "0"
                try:
                    slow_read_h = LatencyHistogram()
                    await sweep(hist=slow_read_h)
                finally:
                    os.environ.pop("CEPH_TPU_INFERENCE", None)
            finally:
                cluster.osds[slow].msgr.inject_internal_delays = 0
            slow_rel = max(
                float(np.linalg.norm(a["scores"] - b["scores"]) /
                      max(np.linalg.norm(b["scores"]), 1e-12))
                for a, b in zip(res_slow, res_exact))

            stages = {}
            infer_counters: dict = {}
            for osd in cluster.osds.values():
                for stage, row in osd.tracer.stage_perf().items():
                    if "infer" not in stage:
                        continue
                    agg = stages.setdefault(
                        stage, {"count": 0, "p99_ms": 0.0})
                    agg["count"] += row.get("count", 0)
                    agg["p99_ms"] = max(agg["p99_ms"],
                                        row.get("p99_ms", 0.0))
                for key, v in osd.inference.perf_dump().items():
                    if isinstance(v, int):
                        infer_counters[key] = \
                            infer_counters.get(key, 0) + v
            base_p99 = base_h.percentile(0.99) or 0.0
            coded_p99 = slow_h.percentile(0.99) or 0.0
            read_p99 = slow_read_h.percentile(0.99) or 0.0
            return {
                "inference_ops": n_ops,
                "inference_queries_per_op": nq,
                "inference_coded_s": round(coded_s, 3),
                "inference_exact_s": round(exact_s, 3),
                "inference_read_then_infer_s": round(read_s, 3),
                "inference_speedup_vs_read_x": round(
                    read_s / max(coded_s, 1e-9), 2),
                "inference_params_bytes": params_bytes,
                "inference_coded_wire_bytes": coded_bytes,
                "inference_read_wire_bytes": read_bytes,
                "inference_bytes_ratio": round(
                    read_bytes / max(coded_bytes, 1), 1),
                "inference_killswitch_parity": int(parity),
                "inference_max_rel_err": round(max_rel, 9),
                "inference_max_est_error": round(max_est, 9),
                "inference_accuracy_ok": int(max_rel <= budget),
                "inference_modes": modes,
                "inference_osd_counters": infer_counters,
                "inference_straggler_delay_s": delay,
                "inference_straggler_base_p99_ms": round(
                    base_p99 * 1e3, 3),
                "inference_straggler_coded_p99_ms": round(
                    coded_p99 * 1e3, 3),
                "inference_straggler_read_p99_ms": round(
                    read_p99 * 1e3, 3),
                "inference_straggler_flat": int(
                    coded_p99 < max(2.0 * base_p99,
                                    base_p99 + 0.5 * delay)),
                "inference_straggler_accuracy_ok": int(
                    slow_rel <= budget),
                "inference_stage_ms": {
                    k: {"count": v["count"],
                        "p99_ms": round(v["p99_ms"], 3)}
                    for k, v in sorted(stages.items())},
            }
        finally:
            await cluster.stop()

    return asyncio.run(run())


def bench_xsched() -> dict:
    """Codec-compiler acceptance sweep (ROADMAP item 4): bitmatrix
    encode AND decode GiB/s at small chunks (~0.5 KiB through
    64 KiB), compiled XOR schedule vs the CEPH_TPU_XSCHED=0 naive
    row-walk.  With the native fused tape executor the scheduled
    mode is ONE C++ dispatch per encode, so the small-chunk delta IS
    the XOR-count + dispatch-discipline cut — exactly the regime
    where every other landed win (batching, mesh, group commit) is
    already amortized.  The <=2 KiB rows roll up into an explicit
    `xsched_small_band` block (the ISSUE-17 acceptance band: ~1x at
    the seed, >=3x required).  A live-cluster leg cites the PR-10
    per-stage histograms (the `encode_inline` stage self-time per
    mode) per the ROADMAP acceptance discipline.  Bit-exactness
    across modes is asserted on every leg."""
    import asyncio

    from ceph_tpu.ec.registry import create_erasure_code

    iters = 2 if _SMOKE else 9
    rng = np.random.default_rng(23)

    def timed(fn) -> float:
        fn()                    # warm: schedule compiles + caches
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def with_mode(on: bool, fn):
        prev = os.environ.get("CEPH_TPU_XSCHED")
        os.environ["CEPH_TPU_XSCHED"] = "1" if on else "0"
        try:
            return fn()
        finally:
            if prev is None:
                os.environ.pop("CEPH_TPU_XSCHED", None)
            else:
                os.environ["CEPH_TPU_XSCHED"] = prev

    from ceph_tpu.ec import xsched as _xs

    xs_before = _xs.stats()
    sweep = {}
    for tech, w in (("liber8tion", 8), ("liberation", 7)):
        for target in (1 << 10, 2 << 10, 4 << 10, 16 << 10,
                       64 << 10):
            # packetsize scales with the chunk (the jerasure cache
            # discipline): region bytes = chunk/w is what the XOR
            # executor streams per op — the measured crossover where
            # the schedule's op-count cut beats numpy call overhead
            # sits near 4 KiB regions
            ps = max(target // (2 * w) // 16 * 16, 16)
            codec = create_erasure_code({
                "plugin": "ec_jax", "technique": tech, "k": "4",
                "m": "2", "w": str(w), "packetsize": str(ps)})
            n = codec.k + codec.m
            align = codec.get_alignment()
            total = max(round(target * codec.k / align), 1) * align
            payload = rng.integers(0, 256, total,
                                   dtype=np.uint8).tobytes()
            enc_gibs, enc_bytes = {}, {}
            for mode in ("sched", "naive"):
                on = mode == "sched"
                enc_bytes[mode] = with_mode(
                    on, lambda: codec.encode(range(n), payload))
                t = with_mode(on, lambda: timed(
                    lambda: codec.encode(range(n), payload)))
                enc_gibs[mode] = total / t / (1 << 30)
            assert {i: bytes(b)
                    for i, b in enc_bytes["sched"].items()} == \
                {i: bytes(b) for i, b in enc_bytes["naive"].items()}, \
                f"{tech}: scheduled parity != naive parity"
            encoded = enc_bytes["sched"]
            chunk_len = len(encoded[0])
            # two erasures, one data + one parity — the RAID-6 worst
            # case, served by the shared inverted submatrix
            avail = {i: bytes(encoded[i]) for i in range(n)
                     if i not in (0, n - 1)}
            dec_gibs, dec_out = {}, {}
            for mode in ("sched", "naive"):
                on = mode == "sched"
                dec_out[mode] = with_mode(
                    on, lambda: codec.decode(range(n), avail,
                                             chunk_len))
                t = with_mode(on, lambda: timed(
                    lambda: codec.decode(range(n), avail,
                                         chunk_len)))
                dec_gibs[mode] = total / t / (1 << 30)
            assert all(bytes(dec_out["sched"][i]) ==
                       bytes(dec_out["naive"][i]) for i in range(n))
            sweep[f"{tech}_{chunk_len}B"] = {
                "chunk_bytes": chunk_len,
                "encode_sched_gibs": round(enc_gibs["sched"], 3),
                "encode_naive_gibs": round(enc_gibs["naive"], 3),
                "encode_speedup": round(
                    enc_gibs["sched"] / enc_gibs["naive"], 3),
                "decode_sched_gibs": round(dec_gibs["sched"], 3),
                "decode_naive_gibs": round(dec_gibs["naive"], 3),
                "decode_speedup": round(
                    dec_gibs["sched"] / dec_gibs["naive"], 3),
            }

    xs_after = _xs.stats()
    # the ISSUE-17 acceptance band, called out explicitly: every
    # sweep row whose chunk is <=2 KiB, with the min/median encode
    # speedup — the seed sat at ~1x here, the native fused executor
    # must clear >=3x
    small = {name: row["encode_speedup"]
             for name, row in sweep.items()
             if row["chunk_bytes"] <= (2 << 10)}
    small_band = {
        "chunks": small,
        "min_encode_speedup": round(min(small.values()), 3),
        "median_encode_speedup": round(
            float(np.median(list(small.values()))), 3),
        "native_execs": xs_after["exec_native"]
        - xs_before["exec_native"],
        "host_execs": xs_after["exec_host"] - xs_before["exec_host"],
    } if small else {}

    # live-cluster leg: the same writes through real daemons per
    # mode, the win cited in the per-stage critical-path histograms
    # (PR-10 discipline — "faster" must name the stage).  The leg
    # runs IN the acceptance regime: 64 KiB chunks (w=8, ps=8 KiB),
    # where the schedule's XOR cut is memory-bound, not numpy-call-
    # overhead-bound
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    profile = {"plugin": "ec_jax", "technique": "liber8tion",
               "k": "4", "m": "2", "w": "8", "packetsize": "8192",
               "crush-failure-domain": "osd"}
    nobj = 4 if _SMOKE else 16
    payload = rng.integers(0, 256, 4 * 8 * 8192,
                           dtype=np.uint8).tobytes()

    async def cluster_leg() -> dict:
        from ceph_tpu.loadgen.stats import LatencyHistogram

        cluster = Cluster(num_osds=6, osds_per_host=6)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "xsbench", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("xsbench")
            for i in range(nobj):
                await io.write_full(f"o{i}", payload)
                got = await io.read(f"o{i}")
                assert bytes(got) == payload  # parity per mode
            merged: dict = {}
            for osd in cluster.osds.values():
                for stage, h in osd.tracer.stage_hist.items():
                    agg = merged.setdefault(stage,
                                            LatencyHistogram())
                    agg.merge(h)
            out = {}
            for stage, h in sorted(merged.items()):
                p50 = h.percentile(0.5)
                out[stage] = round((p50 or 0.0) * 1e3, 3)
            return out
        finally:
            await cluster.stop()

    stage_p50 = {}
    for mode in ("sched", "naive"):
        stage_p50[mode] = with_mode(
            mode == "sched", lambda: asyncio.run(cluster_leg()))
    # the cited stage: the bitmatrix codecs take the INLINE encode
    # path, whose span (`encode_inline`, added with this bench) is
    # exactly the codec work — the XOR cut must show up THERE, not
    # hide in an end-to-end blur; service-batched profiles show as
    # encode_wait instead
    cited = next((s for s in ("encode_inline", "encode_wait",
                              "osd_op")
                  if any(s in stage_p50[m] for m in stage_p50)),
                 "osd_op")
    encode_stage = {mode: stage_p50[mode].get(cited)
                    for mode in ("sched", "naive")}
    return {"xsched_sweep": sweep,
            "xsched_small_band": small_band,
            "xsched_cluster_stage_p50_ms": stage_p50,
            "xsched_cited_stage": cited,
            "xsched_cited_stage_p50_ms": encode_stage}


def bench_smallop() -> dict:
    """Small-op band under open-loop load (ISSUE 17 acceptance): 4 KiB
    objects against a live 6-OSD bitmatrix EC cluster (liber8tion
    k=4 m=2, w=8 ps=512 -> 4 KiB chunks, so every write is sub-chunk),
    driven by the loadgen open-loop harness — latency measured from
    SCHEDULED arrival, so queueing shows up in p99 instead of slowing
    the generator.  Two modes: the native fused-XOR executor +
    sub-chunk op fast lane ON (this PR) vs the
    CEPH_TPU_NATIVE_XSCHED=0 + CEPH_TPU_OP_FAST_LANE=0 host/queued
    configuration (the seed's small-op path).  Reports ops/s + p99
    per mode, and names the per-stage win (PR-10 discipline): the
    merged critical-path stage histograms per mode, the fast-lane
    grant counters, and the xsched native/tape counter deltas that
    attribute the encode-side cut."""
    import asyncio

    from ceph_tpu.ec import xsched
    from ceph_tpu.loadgen.runner import run_open_loop
    from ceph_tpu.loadgen.stats import LatencyHistogram
    from ceph_tpu.loadgen.targets import RadosTarget
    from ceph_tpu.loadgen.workload import make_tenants

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    profile = {"plugin": "ec_jax", "technique": "liber8tion",
               "k": "4", "m": "2", "w": "8", "packetsize": "512",
               "crush-failure-domain": "osd", "stripe_unit": "4096"}
    obj_size = 4096
    if _SMOKE:
        tenants_n, rate, duration = 8, 30.0, 0.5
        sat_rate, sat_duration, sat_cap = 60.0, 0.4, 100
    else:
        # cruise: ~65% of the in-process cluster's measured small-op
        # capacity (~200 ops/s) — below the knee, so p99 measures
        # the pipeline, not open-loop queue collapse.  saturate:
        # offered well past the knee with a bounded in-flight cap —
        # completions/s IS the capacity, where the native executor's
        # per-op CPU cut becomes throughput
        tenants_n, rate, duration = 22, 6.0, 6.0
        sat_rate, sat_duration, sat_cap = 30.0, 4.0, 400
    # write-heavy: the encode path is where the native tape + fast
    # lane bite; the read leg keeps the decode path honest
    blend = {"write": 0.6, "read": 0.3, "stat": 0.1}

    async def leg() -> dict:
        cluster = Cluster(num_osds=6, osds_per_host=6)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "smallop", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("smallop")
            target = RadosTarget(io)
            await target.setup(objects=32, object_size=obj_size)
            # warm the pipeline before the measured window: codec +
            # tape compiles, native lib load and PG paths must not
            # land in one mode's tail
            for i in range(8):
                await io.write_full(f"warm-{i}", b"w" * obj_size)
                await io.read(f"warm-{i}")
            for osd in cluster.osds.values():
                osd.tracer.stage_hist.clear()
            xs0 = dict(xsched.stats())
            tenants = make_tenants(tenants_n, rate=rate, blend=blend,
                                   objects=32, object_size=obj_size,
                                   name_prefix="so")
            rep = await run_open_loop(target, tenants, duration,
                                      seed=0xEC)
            xs1 = xsched.stats()
            stages: dict = {}
            fast_lane = granted = 0
            for osd in cluster.osds.values():
                st = osd.scheduler.stats()
                fast_lane += sum(st.get("fast_lane", {}).values())
                granted += sum(st.get("granted", {}).values())
                for stage, h in osd.tracer.stage_hist.items():
                    agg = stages.setdefault(stage, LatencyHistogram())
                    agg.merge(h)
            stage_p50 = {s: round((h.percentile(0.5) or 0.0) * 1e3, 4)
                         for s, h in sorted(stages.items())}
            # saturation window on the same warm cluster: offered
            # far past the knee, in-flight bounded so the drain is
            # bounded too — completions/s measures capacity
            sat = await run_open_loop(
                target,
                make_tenants(tenants_n, rate=sat_rate, blend=blend,
                             objects=32, object_size=obj_size,
                             name_prefix="sa"),
                sat_duration, seed=0xEC + 1,
                max_outstanding=sat_cap, drain_timeout=10.0)
            return {
                "ops_per_sec": rep["ops_per_sec"],
                "p50_ms": rep["p50_ms"],
                "p99_ms": rep["p99_ms"],
                "completed": rep["completed"],
                "errors": rep["errors"],
                "stage_p50_ms": stage_p50,
                "fast_lane_grants": fast_lane,
                "grants": granted,
                "saturated_ops_per_sec": sat["ops_per_sec"],
                "saturated_offered": sat["offered"],
                "saturated_dropped": sat["dropped"],
                "xsched_delta": {
                    key: xs1[key] - xs0[key]
                    for key in ("exec_native", "exec_host",
                                "tape_hits", "tape_misses")},
            }
        finally:
            await cluster.stop()

    def with_env(on: bool, fn):
        keys = ("CEPH_TPU_NATIVE_XSCHED", "CEPH_TPU_OP_FAST_LANE")
        prev = {key: os.environ.get(key) for key in keys}
        for key in keys:
            os.environ[key] = "1" if on else "0"
        try:
            return fn()
        finally:
            for key, val in prev.items():
                if val is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = val

    modes = {}
    for mode in ("native", "host"):
        modes[mode] = with_env(mode == "native",
                               lambda: asyncio.run(leg()))
    # the cited stage: sub-chunk writes on the native path skip the
    # scheduler queue (fast lane) and run the encode inline through
    # the fused tape — so the win must show in the write-path encode
    # stage, not an end-to-end blur
    cited = next((s for s in ("encode_inline", "encode_wait",
                              "osd_op")
                  if any(s in modes[m]["stage_p50_ms"]
                         for m in modes)), "osd_op")
    n, h = modes["native"], modes["host"]
    return {"smallop_modes": modes,
            "smallop_object_bytes": obj_size,
            "smallop_capacity_speedup": round(
                n["saturated_ops_per_sec"]
                / h["saturated_ops_per_sec"], 3)
            if h["saturated_ops_per_sec"] else None,
            "smallop_ops_speedup": round(
                n["ops_per_sec"] / h["ops_per_sec"], 3)
            if h["ops_per_sec"] else None,
            "smallop_p99_ratio": round(h["p99_ms"] / n["p99_ms"], 3)
            if n["p99_ms"] else None,
            "smallop_cited_stage": cited,
            "smallop_cited_stage_p50_ms": {
                m: modes[m]["stage_p50_ms"].get(cited)
                for m in modes}}


def _load_probe() -> Optional[dict]:
    """Pre-contract probe of the open-loop load harness
    (ceph_tpu/loadgen): a thousand simulated tenants (smoke: 200)
    fire Poisson-scheduled mixed ops at the embedded cluster, latency
    measured from SCHEDULED arrival (queueing delay counted, the
    open-loop discipline), percentiles streamed through the bounded
    log-bucket histogram.  Schedule determinism is asserted
    (fingerprint equality across two generations).  Goodput +
    p50/p95/p99 land in the contract line's `load` key; None (with a
    stderr note) when the probe cannot run."""
    if _remaining() < 0:
        print("# load probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_LOAD_PROBE_TIMEOUT", "60"))
    try:
        import asyncio

        from ceph_tpu.loadgen import (
            make_tenants, run_embedded, schedule_fingerprint,
        )

        n_tenants = 200 if _SMOKE else 1000
        duration = 0.5 if _SMOKE else 1.5
        tenants = make_tenants(n_tenants, rate=2.0, zipf_theta=1.1,
                               objects=64, object_size=4096)
        deterministic = int(
            schedule_fingerprint(tenants[:64], duration, seed=11)
            == schedule_fingerprint(tenants[:64], duration, seed=11))
        rep = asyncio.run(asyncio.wait_for(
            run_embedded(tenants, duration=duration, seed=11),
            probe_timeout))
        return {
            "tenants": rep["tenants"],
            "offered": rep["offered"],
            "completed": rep["completed"],
            "shed": rep["shed"],
            "errors": rep["errors"],
            "goodput_mib_s": rep["goodput_mib_s"],
            "ops_per_sec": rep["ops_per_sec"],
            "p50_ms": rep["p50_ms"],
            "p95_ms": rep["p95_ms"],
            "p99_ms": rep["p99_ms"],
            "deterministic": deterministic,
        }
    except Exception as e:
        print(f"# load probe failed: {e!r}", file=sys.stderr)
        return None


def bench_load() -> dict:
    """Open-loop sweep to the knee: the same 1000-tenant population
    at doubling per-tenant arrival rates until goodput stops scaling
    with offered load (completed/offered falls or p99 blows through
    the knee threshold).  The open-loop discipline is what makes the
    knee visible: a closed-loop bench would slow its own offering and
    report a flattering plateau instead."""
    import asyncio

    from ceph_tpu.loadgen import make_tenants, run_embedded
    from ceph_tpu.rados.embedded import LocalCluster

    n_tenants = 200 if _SMOKE else 1000
    duration = 0.5 if _SMOKE else 2.0
    steps = 3 if _SMOKE else 6
    out: dict = {"load_sweep": []}
    knee = None
    cluster = LocalCluster(num_osds=6)
    try:
        cluster.create_replicated_pool("loadgen", size=2, pg_num=16)
        for i in range(steps):
            rate = 2.0 * (2 ** i)
            tenants = make_tenants(n_tenants, rate=rate,
                                   zipf_theta=1.1, objects=64,
                                   object_size=4096)
            rep = asyncio.run(run_embedded(
                tenants, duration=duration, seed=17,
                cluster=cluster))
            row = {"rate_per_tenant": rate,
                   "offered": rep["offered"],
                   "completed": rep["completed"],
                   "dropped": rep["dropped"],
                   "goodput_mib_s": rep["goodput_mib_s"],
                   "p50_ms": rep["p50_ms"],
                   "p99_ms": rep["p99_ms"]}
            out["load_sweep"].append(row)
            done_ratio = rep["completed"] / max(rep["offered"], 1)
            if knee is None and (done_ratio < 0.95
                                 or (rep["p99_ms"] or 0) > 100.0):
                knee = rate
    finally:
        cluster.shutdown()
    out["load_knee_rate_per_tenant"] = knee
    out["load_peak_goodput_mib_s"] = max(
        (r["goodput_mib_s"] for r in out["load_sweep"]), default=None)
    return out


def _durability_probe() -> Optional[dict]:
    """Pre-contract probe of the crash-consistency layer
    (ceph_tpu/os/faultstore.py): a smoke power-cut sweep over a mixed
    TPUStore workload — every explored crash point must satisfy the
    invariants (mount succeeds, acked txns visible, replay idempotent,
    csums clean, freelist/blob map consistent) — plus the harness
    SELF-TEST: the same sweep pointed at a store with its pre-commit
    fsync removed must report violations.  Counters land in the
    contract line's `durability` key; None (with a stderr note) when
    the probe cannot run.

    Contract-first discipline: skipped when the wall-clock budget is
    spent; the body runs on a daemon thread under a hard timeout so a
    wedged filesystem cannot park the bench past the contract line.
    Smoke sizing via CEPH_TPU_BENCH_DURABILITY_TXNS/_POINTS."""
    return _probe_on_daemon_thread(
        "durability", _durability_probe_body,
        "CEPH_TPU_BENCH_DURABILITY_PROBE_TIMEOUT", "90")


def _durability_probe_body() -> dict:
    """The probe proper; failures propagate to the runner thread's
    capture in _durability_probe — one reporting layer."""
    import shutil
    import tempfile

    from ceph_tpu.os.faultstore import BrokenBlockStore, CrashSweep

    txns = int(os.environ.get("CEPH_TPU_BENCH_DURABILITY_TXNS",
                              "8" if _SMOKE else "16"))
    max_points = int(os.environ.get(
        "CEPH_TPU_BENCH_DURABILITY_POINTS",
        "60" if _SMOKE else "150"))
    workdir = tempfile.mkdtemp(prefix="bench-durability-")
    try:
        rep = CrashSweep(os.path.join(workdir, "good")).run(
            txns=txns, max_points=max_points)
        broken = CrashSweep(os.path.join(workdir, "broken"),
                            store_cls=BrokenBlockStore).run(
            txns=max(4, txns // 2), max_points=max_points,
            double_crash=False)
        return {
            "points": rep["points"],
            "distinct_images": rep["distinct_images"],
            "double_crash_points": rep["double_crash_points"],
            "violations": len(rep["violations"]),
            "broken_store_caught": int(bool(broken["violations"])),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_durability() -> dict:
    """The FULL crash sweep (every cut, every schedule, double-crash
    legs) over a larger workload — the acceptance-sized run (>= 200
    distinct crash points, zero violations), budget-gated like every
    optional section."""
    import shutil
    import tempfile

    from ceph_tpu.os.faultstore import CrashSweep

    workdir = tempfile.mkdtemp(prefix="bench-durability-full-")
    try:
        t0 = time.monotonic()
        rep = CrashSweep(workdir).run(txns=24)
        return {
            "durability_points": rep["points"],
            "durability_distinct_images": rep["distinct_images"],
            "durability_double_crash_points":
                rep["double_crash_points"],
            "durability_violations": len(rep["violations"]),
            "durability_sweep_seconds": time.monotonic() - t0,
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_qos() -> dict:
    """QoS isolation proof on a live cluster: tenant B runs a steady
    light workload while tenant A's offered load goes 10x, with the
    per-tenant mClock profiles + admission gate ON vs OFF
    (CEPH_TPU_QOS).  The number that matters: B's p99 degradation
    across the 1x -> 10x step — bounded with QoS on (A's excess is
    shed at the front door), unbounded-ish with it off (B queues
    behind A's flood in the shared class)."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster
    from ceph_tpu.loadgen import (
        RadosTarget, TenantSpec, run_open_loop,
    )

    duration = 2.0 if _SMOKE else 4.0
    # The contention is real ASYNC service time, not host CPU (which
    # a single-process cluster would charge to both tenants alike):
    # EC reads of a tiny shared hot set force remote sub-reads, and
    # ms_inject_internal_delays on every OSD makes each sub-read
    # round trip cost ~5 ms while the CPU stays idle.  With one grant
    # slot per OSD the serving primary's capacity is ~100 ops/s —
    # A's 10x flood (300/s) oversubscribes it 3x, which is exactly
    # the regime QoS exists for.  A's mClock limit sits at ~its 1x
    # offer (held cluster-wide by the delta/rho piggyback,
    # CEPH_TPU_DMCLOCK); B rides a reservation.  The read tier is disabled for both legs — it
    # would serve the hot set from memory and measure cache
    # residency, not scheduling.
    a_rate, b_rate = 30.0, 10.0
    osize = 64 << 10
    n_objs = 2
    delay = 0.005
    profiles = json.dumps({"A": [0.0, 1.0, 40.0],
                           "B": [20.0, 5.0, 0.0]})
    ec_profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
                  "k": "2", "m": "2", "crush-failure-domain": "osd"}

    async def run_leg(mult: float) -> dict:
        cluster = Cluster(
            num_osds=6, osds_per_host=3,
            osd_config={"osd_heartbeat_interval": 3.0,
                        "osd_heartbeat_grace": 20.0,
                        "osd_op_num_threads": 1,
                        "osd_mclock_tenant_profiles": profiles,
                        "osd_mclock_admission_max_delay_ms": 10.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "qos", profile=ec_profile, pg_num=8)
            io = cluster.client.open_ioctx("qos")
            target = RadosTarget(io)
            await target.setup(n_objs, osize)
            for osd in cluster.osds.values():
                osd.msgr.inject_internal_delays = delay
            tenants = [
                TenantSpec(name="A", arrival_rate=a_rate * mult,
                           blend={"read": 1.0}, zipf_theta=0.0,
                           objects=n_objs, object_size=osize),
                TenantSpec(name="B", arrival_rate=b_rate,
                           blend={"read": 1.0}, zipf_theta=0.0,
                           objects=n_objs, object_size=osize),
            ]
            rep = await run_open_loop(target, tenants,
                                      duration=duration, seed=23,
                                      per_tenant=("A", "B"),
                                      drain_timeout=60.0)
            shed = 0
            for osd in cluster.osds.values():
                shed += osd.admission.counters.get("shed", 0)
            rep["admission_shed"] = shed
            return rep
        finally:
            await cluster.stop()

    def legs() -> dict:
        one = asyncio.run(run_leg(1.0))
        ten = asyncio.run(run_leg(10.0))
        return {"b_p99_1x_ms": one["per_tenant"]["B"]["p99_ms"],
                "b_p99_10x_ms": ten["per_tenant"]["B"]["p99_ms"],
                "b_completed_10x": ten["per_tenant"]["B"]["completed"],
                "a_completed_10x": ten["per_tenant"]["A"]["completed"],
                "a_shed_10x": ten["per_tenant"]["A"]["shed"],
                "admission_shed_10x": ten["admission_shed"]}

    prev = os.environ.get("CEPH_TPU_QOS")
    prev_tier = os.environ.get("CEPH_TPU_TIER")
    try:
        os.environ["CEPH_TPU_TIER"] = "0"
        os.environ["CEPH_TPU_QOS"] = "1"
        on = legs()
        os.environ["CEPH_TPU_QOS"] = "0"
        off = legs()
    finally:
        for name, val in (("CEPH_TPU_QOS", prev),
                          ("CEPH_TPU_TIER", prev_tier)):
            if val is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = val

    def ratio(leg):
        base = max(leg["b_p99_1x_ms"] or 1e-9, 1e-9)
        return round((leg["b_p99_10x_ms"] or 0.0) / base, 3)

    # "held": B's p99 within 25% of its 1x baseline, or under an
    # absolute 25 ms floor (single-host noise below which per-op
    # jitter, not tenant interference, dominates the ratio)
    held = bool((on["b_p99_10x_ms"] or float("inf"))
                <= max(1.25 * (on["b_p99_1x_ms"] or 0.0), 25.0))
    return {
        "qos_on": on, "qos_off": off,
        "qos_b_p99_degradation_on_x": ratio(on),
        "qos_b_p99_degradation_off_x": ratio(off),
        "qos_isolation_held": held,
    }


def _chaos_probe() -> Optional[dict]:
    """Pre-contract probe of the compound-chaos engine
    (ceph_tpu/chaos/): a seeded composed 3-hazard scenario —
    messenger stragglers x probabilistic device faults x live
    kill-switch flips — over open-loop two-tenant traffic on a live
    loopback cluster, with every invariant monitor armed (zero client
    errors, bit-exact readback, durability sweep, leak audit).  The
    counters land in the contract line's `chaos` key with the seed
    echoed, so a violating round replays from the contract line
    alone.  None (with a stderr note) when the probe cannot run."""
    return _probe_on_daemon_thread(
        "chaos", _chaos_probe_body,
        "CEPH_TPU_BENCH_CHAOS_PROBE_TIMEOUT", "120")


def _chaos_probe_body() -> dict:
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster
    from ceph_tpu.chaos import compose, run_scenario
    from ceph_tpu.loadgen import TenantSpec

    seed = int(os.environ.get("CEPH_TPU_BENCH_CHAOS_SEED", "20107"))
    duration = float(os.environ.get("CEPH_TPU_BENCH_CHAOS_S",
                                    "3.0" if _SMOKE else "5.0"))

    async def run() -> dict:
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            sc = compose(
                seed=seed, duration=duration,
                tenants=[TenantSpec(f"t{i}", arrival_rate=30.0,
                                    objects=16, object_size=4096)
                         for i in range(2)],
                osd_ids=[0, 1, 2, 3],
                hazards=("straggler", "device_fail", "kill_switch"),
                p99_bounds={"t0": 5000.0, "t1": 5000.0},
                objects=16, object_size=4096)
            return await run_scenario(cluster, sc)
        finally:
            await cluster.stop()

    rep = asyncio.run(asyncio.wait_for(run(), 110))
    return {
        "seed": rep["seed"],
        "duration_s": duration,
        "events_fired": len(rep["events_fired"]),
        "hazards": sorted({e["hazard"]
                           for e in rep["events_fired"]}),
        "reads_verified": rep["reads_verified"],
        "acked_writes_swept": rep["acked_writes_swept"],
        "flag_flips": rep["flag_flips"],
        "errors": rep["loadgen"]["errors"],
        "violations": len(rep["violations"]),
    }


def bench_chaos() -> dict:
    """The full compound matrix, budget-gated: >= 20 s of open-loop
    three-tenant traffic x all six hazard kinds (stragglers, device
    faults, host loss, kill-switch flips, power-cut kill/revive on
    persistent FaultStore OSDs, drain/backfill) with zero tolerated
    violations, plus the dmClock delta/rho legs: a limit-capped
    tenant's completed rate with the piggyback ON (~its limit,
    cluster-wide) vs OFF (~N_primaries x its limit, the per-OSD-only
    hole).  The worst completed op's retained trace tree ships in
    bench_details.json as the exemplar even on a green run."""
    import asyncio
    import shutil
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster, tpustore_factory
    from ceph_tpu.chaos import compose, run_scenario
    from ceph_tpu.chaos.monitors import capture_worst_op
    from ceph_tpu.common import flags
    from ceph_tpu.loadgen import (
        RadosTarget, TenantSpec, run_open_loop,
    )

    seed = int(os.environ.get("CEPH_TPU_BENCH_CHAOS_SEED", "20107"))
    duration = float(os.environ.get("CEPH_TPU_BENCH_CHAOS_FULL_S",
                                    "25.0"))
    t0 = time.monotonic()
    workdir = tempfile.mkdtemp(prefix="bench-chaos-")
    prev_ci = flags.peek("CEPH_TPU_CRASH_INJECT")
    flags.set_flag("CEPH_TPU_CRASH_INJECT", "1")

    async def matrix() -> dict:
        cluster = Cluster(num_osds=6, persistent=True,
                          store_factory=tpustore_factory(
                              workdir, fault=True),
                          osd_config={"osd_max_backfills": 1})
        await cluster.start()
        try:
            sc = compose(
                seed=seed, duration=duration,
                tenants=[TenantSpec(f"t{i}", arrival_rate=25.0,
                                    objects=24, object_size=8192)
                         for i in range(3)],
                osd_ids=list(range(6)),
                hazards=("straggler", "device_fail", "host_down",
                         "kill_switch", "powercut", "drain"),
                persistent_osds=list(range(1, 6)),
                protected_osds=[0],
                p99_bounds={f"t{i}": 10_000.0 for i in range(3)},
                objects=24, object_size=8192)
            rep = await run_scenario(cluster, sc, pool_size=3)
            # exemplar even when green: the slowest op the storm
            # produced, with its retained span tree when the tail
            # policy kept one
            rep.setdefault("worst_op", capture_worst_op(cluster))
            return rep
        finally:
            await cluster.stop()

    async def dmclock_leg(enabled: str) -> dict:
        profiles = json.dumps({"capped": [0.0, 1.0, 25.0]})
        cluster = Cluster(num_osds=4, osd_config={
            "osd_mclock_tenant_profiles": profiles})
        await cluster.start()
        prev = flags.peek("CEPH_TPU_DMCLOCK")
        flags.set_flag("CEPH_TPU_DMCLOCK", enabled)
        try:
            await cluster.client.create_replicated_pool(
                "qos", size=2, pg_num=32)
            target = RadosTarget(cluster.client.open_ioctx("qos"))
            await target.setup(32, 4096)
            rep = await run_open_loop(
                target,
                [TenantSpec("capped", arrival_rate=80.0,
                            blend={"read": 1.0}, objects=32,
                            object_size=4096)],
                4.0, seed=seed, per_tenant=["capped"])
            t = rep["per_tenant"]["capped"]
            return {"rate_ops_s": round(
                        t["completed"] / max(rep["elapsed_s"], 1e-9),
                        2),
                    "p99_ms": t["p99_ms"],
                    "errors": rep["errors"]}
        finally:
            if prev is None:
                flags.clear("CEPH_TPU_DMCLOCK")
            else:
                flags.set_flag("CEPH_TPU_DMCLOCK", prev)
            await cluster.stop()

    try:
        rep = asyncio.run(asyncio.wait_for(matrix(), 300))
        dm_on = asyncio.run(asyncio.wait_for(dmclock_leg("1"), 120))
        dm_off = asyncio.run(asyncio.wait_for(dmclock_leg("0"), 120))
    finally:
        if prev_ci is None:
            flags.clear("CEPH_TPU_CRASH_INJECT")
        else:
            flags.set_flag("CEPH_TPU_CRASH_INJECT", prev_ci)
        shutil.rmtree(workdir, ignore_errors=True)

    per_tenant = {
        name: {"p99_ms": t.get("p99_ms"),
               "ops_per_sec": t.get("ops_per_sec"),
               "goodput_mib_s": t.get("goodput_mib_s"),
               "errors": t.get("errors")}
        for name, t in rep["loadgen"].get("per_tenant", {}).items()}
    return {
        "chaos_seed": rep["seed"],
        "chaos_duration_s": duration,
        "chaos_events_fired": len(rep["events_fired"]),
        "chaos_hazards": sorted({e["hazard"]
                                 for e in rep["events_fired"]}),
        "chaos_powercuts": rep["powercuts"],
        "chaos_reads_verified": rep["reads_verified"],
        "chaos_acked_writes_swept": rep["acked_writes_swept"],
        "chaos_flag_flips": rep["flag_flips"],
        "chaos_violations": rep["violations"],
        "chaos_per_tenant": per_tenant,
        "chaos_worst_op": rep.get("worst_op"),
        "chaos_dmclock_on": dm_on,
        "chaos_dmclock_off": dm_off,
        "chaos_dmclock_separation_x": round(
            dm_off["rate_ops_s"] / max(dm_on["rate_ops_s"], 1e-9),
            2),
        "chaos_seconds": round(time.monotonic() - t0, 1),
    }


def _service_probe() -> Optional[dict]:
    """End-to-end probe of the async micro-batching encode service:
    8 concurrent encodes must produce bit-exact shards/hinfo vs the
    inline path while sharing batched dispatches.  The counters land
    in the contract line so the driver sees the service working; None
    (with a stderr note) when the probe cannot run.

    Contract-first discipline: the probe runs BEFORE _emit_contract,
    so it is hard-bounded — asyncio.wait_for caps the event loop (a
    service defect that strands a future must not hang the bench) and
    an exhausted wall-clock budget skips it outright."""
    import asyncio

    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.encode_service import EncodeService

    if _remaining() < 0:
        print("# encode service probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_SERVICE_PROBE_TIMEOUT", "60"))
    prev = os.environ.get("CEPH_TPU_FUSE_MIN_BYTES")
    os.environ["CEPH_TPU_FUSE_MIN_BYTES"] = "0"  # engage off-TPU too
    try:
        codec = create_erasure_code(
            {"plugin": "ec_jax", "technique": "reed_sol_van",
             "k": "4", "m": "2"})
        sinfo = ec_util.StripeInfo(4, 4 * 1024)
        rng = np.random.default_rng(11)
        bufs = [rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
                for _ in range(8)]

        async def run():
            svc = EncodeService(who="bench-probe")
            outs = await asyncio.gather(
                *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                        logical_len=len(b))
                  for b in bufs))
            st = svc.stats()
            await svc.stop()
            return outs, st

        outs, st = asyncio.run(
            asyncio.wait_for(run(), timeout=probe_timeout))
        for b, (shards, hinfo, crc) in zip(bufs, outs):
            ws, wh, wc = ec_util.encode_with_hinfo(
                sinfo, codec, b, range(6), logical_len=len(b))
            assert crc == wc and hinfo.cumulative_shard_hashes == \
                wh.cumulative_shard_hashes, "service hinfo mismatch"
            assert all(bytes(shards[i]) == bytes(ws[i])
                       for i in range(6)), "service shard mismatch"
        return {key: st[key] for key in ("requests", "batched",
                                         "inline", "shed", "batches")}
    except Exception as e:
        print(f"# encode service probe failed: {e!r}", file=sys.stderr)
        return None
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_FUSE_MIN_BYTES", None)
        else:
            os.environ["CEPH_TPU_FUSE_MIN_BYTES"] = prev


def _group_commit_probe() -> Optional[dict]:
    """Pre-contract probe of the TPUStore group-commit lane
    (os/groupcommit.py): N concurrent durable writes through the
    GroupCommitter must buy FEWER barriers than writers (fsyncs and
    kv sync commits < N) with bit-exact readback, while the kill
    switch leg pays exactly one commit per txn (behavior parity).
    Counters land in the contract line's `group_commit` key; None
    (with a stderr note) when the probe cannot run.

    Contract-first discipline: runs before _emit_contract under a
    hard asyncio.wait_for, on a throwaway store in a tempdir."""
    import asyncio
    import shutil
    import tempfile

    from ceph_tpu.os import ObjectId, Transaction
    from ceph_tpu.os.groupcommit import GroupCommitter
    from ceph_tpu.os.tpustore import TPUStore

    if _remaining() < 0:
        print("# group commit probe skipped: budget exhausted",
              file=sys.stderr)
        return None
    probe_timeout = float(os.environ.get(
        "CEPH_TPU_BENCH_GC_PROBE_TIMEOUT", "60"))
    n = 16
    workdir = tempfile.mkdtemp(prefix="bench-gc-")
    prev = os.environ.get("CEPH_TPU_GROUP_COMMIT")
    try:
        os.environ.pop("CEPH_TPU_GROUP_COMMIT", None)
        store = TPUStore(os.path.join(workdir, "s"))
        store.mkfs()
        store.mount()
        t = Transaction()
        t.create_collection("cc")
        store.queue_transaction(t)
        payloads = {f"o{i}": bytes([i]) * 65536 for i in range(n)}

        def txn(oid: str, data: bytes) -> Transaction:
            t = Transaction()
            t.write("cc", ObjectId(oid), 0, len(data), data)
            return t

        async def leg(suffix: str):
            gc = GroupCommitter(store, window_ms=1.0)
            kv0, fs0 = store.perf["kv_commits"], \
                store.perf["block_fsyncs"]
            await asyncio.gather(
                *(gc.queue_transaction(txn(o + suffix, d))
                  for o, d in payloads.items()))
            await gc.stop()
            return (store.perf["kv_commits"] - kv0,
                    store.perf["block_fsyncs"] - fs0, gc.stats())

        kv_on, fs_on, st = asyncio.run(
            asyncio.wait_for(leg(""), probe_timeout))
        bitexact = int(all(
            store.read("cc", ObjectId(o)) == d
            for o, d in payloads.items()))
        os.environ["CEPH_TPU_GROUP_COMMIT"] = "0"
        kv_off, fs_off, _st_off = asyncio.run(
            asyncio.wait_for(leg("-x"), probe_timeout))
        store.umount()
        return {
            "writers": n,
            "kv_commits": kv_on,
            "fsyncs": fs_on,
            "kv_commits_inline": kv_off,
            "fsyncs_inline": fs_off,
            "fsyncs_lt_writers": int(fs_on < n),
            "bitexact": bitexact,
            "batches": st["batches"],
            "txns_per_batch_avg": st["txns_per_batch_avg"],
            "fsyncs_saved": store.perf["gc_fsyncs_saved"],
        }
    except Exception as e:
        print(f"# group commit probe failed: {e!r}", file=sys.stderr)
        return None
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_GROUP_COMMIT", None)
        else:
            os.environ["CEPH_TPU_GROUP_COMMIT"] = prev
        shutil.rmtree(workdir, ignore_errors=True)


def bench_group_commit() -> dict:
    """p50/p99 end-to-end write latency with TPUStore group commit ON
    vs OFF (CEPH_TPU_GROUP_COMMIT=0) through a persistent-store
    cluster, with the win attributed stage-by-stage: the per-OSD
    critical-path histograms' journal-family stages (kv_commit_wait /
    kv_commit / fsync) ride along for each mode so a drop in the
    commit stage cannot hide a regression elsewhere, and the barrier
    counters prove fsyncs-per-N-concurrent-writes < N."""
    import asyncio
    import shutil
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster, tpustore_factory

    n_ops = 24 if _SMOKE else 48
    osize = 32 << 10
    payload = np.random.default_rng(41).integers(
        0, 256, osize, dtype=np.uint8).tobytes()
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "2", "m": "1", "crush-failure-domain": "osd"}
    journal_stages = ("kv_commit_wait", "kv_commit", "fsync")

    async def run_mode() -> dict:
        workdir = tempfile.mkdtemp(prefix="bench-gc-cluster-")
        cluster = Cluster(num_osds=3, osds_per_host=3,
                          store_factory=tpustore_factory(workdir),
                          persistent=True,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "gcb", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("gcb")
            await io.write_full("warm", payload)  # connections warm
            lats: list = []

            async def one(i: int) -> None:
                t0 = time.perf_counter()
                await io.write_full(f"w{i}", payload)
                lats.append(time.perf_counter() - t0)

            kv0 = sum(o.store.perf["kv_commits"]
                      for o in cluster.osds.values())
            fs0 = sum(o.store.perf["block_fsyncs"]
                      for o in cluster.osds.values())
            await asyncio.gather(*(one(i) for i in range(n_ops)))
            kv = sum(o.store.perf["kv_commits"]
                     for o in cluster.osds.values()) - kv0
            fs = sum(o.store.perf["block_fsyncs"]
                     for o in cluster.osds.values()) - fs0
            from ceph_tpu.loadgen.stats import LatencyHistogram

            stages: dict = {}
            for osd in cluster.osds.values():
                for stage, h in osd.tracer.stage_hist.items():
                    if stage not in journal_stages:
                        continue
                    agg = stages.setdefault(stage,
                                            LatencyHistogram())
                    agg.merge(h)
            stage_out = {}
            for stage, h in sorted(stages.items()):
                p50 = h.percentile(0.5)
                stage_out[stage] = {
                    "count": h.count,
                    "p50_ms": round(p50 * 1e3, 3) if p50 else 0.0,
                    "self_s": round(h.total, 4),
                }
            lats.sort()
            rb = await io.read("w0")
            return {
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
                "p99_ms": round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.99))] * 1e3, 3),
                "kv_commits": kv,
                "fsyncs": fs,
                "stages": stage_out,
                "bitexact": int(bytes(rb) == payload),
            }
        finally:
            await cluster.stop()
            shutil.rmtree(workdir, ignore_errors=True)

    prev = os.environ.get("CEPH_TPU_GROUP_COMMIT")
    try:
        os.environ.pop("CEPH_TPU_GROUP_COMMIT", None)
        on = asyncio.run(run_mode())
        os.environ["CEPH_TPU_GROUP_COMMIT"] = "0"
        off = asyncio.run(run_mode())
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_GROUP_COMMIT", None)
        else:
            os.environ["CEPH_TPU_GROUP_COMMIT"] = prev
    return {
        "group_commit_writes": n_ops,
        "group_commit_p50_on_ms": on["p50_ms"],
        "group_commit_p99_on_ms": on["p99_ms"],
        "group_commit_p50_off_ms": off["p50_ms"],
        "group_commit_p99_off_ms": off["p99_ms"],
        "group_commit_kv_commits_on": on["kv_commits"],
        "group_commit_kv_commits_off": off["kv_commits"],
        "group_commit_fsyncs_on": on["fsyncs"],
        "group_commit_fsyncs_off": off["fsyncs"],
        "group_commit_bitexact": on["bitexact"] and off["bitexact"],
        "group_commit_stages_on": on["stages"],
        "group_commit_stages_off": off["stages"],
    }


def bench_write_path() -> dict:
    """Concurrent-writes throughput through the OSD op engine with the
    micro-batching encode service on vs off: 32 concurrent 256 KiB
    write_fulls into an EC 4+2 pool on an in-loop cluster, best of 3
    trials per mode.  MiB/s of object bytes; per-daemon service
    counters (summed) ride along."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster

    n_objs, osize = 32, 256 << 10
    payloads = [np.random.default_rng(100 + i).integers(
        0, 256, osize, dtype=np.uint8).tobytes()
        for i in range(n_objs)]
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "4", "m": "2", "crush-failure-domain": "osd",
               "stripe_unit": "65536"}

    async def run_mode():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "wp", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("wp")
            best = float("inf")
            for trial in range(3):
                t0 = time.perf_counter()
                await asyncio.gather(
                    *(io.write_full(f"o{trial}-{i}", payloads[i])
                      for i in range(n_objs)))
                dt = time.perf_counter() - t0
                if trial > 0:       # first trial warms connections
                    best = min(best, dt)
            svc: dict = {}
            for osd in cluster.osds.values():
                st = osd.encode_service.stats()
                for key in ("requests", "batched", "inline", "shed",
                            "batches"):
                    svc[key] = svc.get(key, 0) + st[key]
            return n_objs * osize / best / (1 << 20), svc
        finally:
            await cluster.stop()

    prev = os.environ.get("CEPH_TPU_ENCODE_SERVICE")
    try:
        os.environ["CEPH_TPU_ENCODE_SERVICE"] = "1"
        mibs_on, svc_counters = asyncio.run(run_mode())
        os.environ["CEPH_TPU_ENCODE_SERVICE"] = "0"
        mibs_off, _off = asyncio.run(run_mode())
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_ENCODE_SERVICE", None)
        else:
            os.environ["CEPH_TPU_ENCODE_SERVICE"] = prev
    return {"write_burst_32x256KiB_svc_on_mibs": mibs_on,
            "write_burst_32x256KiB_svc_off_mibs": mibs_off,
            "write_burst_encode_service": svc_counters}


def bench_tier() -> dict:
    """Skewed-read leg through a live cluster, read tier on vs off:
    24 x 32 KiB objects in an EC 4+2 pool, 256 zipf(1.2) reads.  The
    decode-dispatch delta from plan.stats() shows the hot-read bypass
    working (tier on: hot objects decode once); the byte-equality
    check shows it is exact."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster
    from ceph_tpu.ec import plan as ec_plan
    from ceph_tpu.tools.rados import zipf_indices

    n_objs, osize, n_reads = 24, 32 << 10, 256
    payloads = [np.random.default_rng(300 + i).integers(
        0, 256, osize, dtype=np.uint8).tobytes()
        for i in range(n_objs)]
    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "4", "m": "2", "crush-failure-domain": "osd"}
    idx = zipf_indices(1.2, n_objs, n_reads, seed=41)

    async def run_mode():
        cluster = Cluster(num_osds=6, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0,
                                      "osd_hit_set_period": 3600.0})
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "tp", profile=profile, pg_num=8)
            io = cluster.client.open_ioctx("tp")
            for i in range(n_objs):
                await io.write_full(f"t{i}", payloads[i])
            # warm pass promotes the hot set, timed pass measures it
            for i in idx[:64]:
                await io.read(f"t{int(i)}")
            await asyncio.sleep(0.2)  # let promotions land
            d0 = ec_plan.stats()["dispatches"]
            t0 = time.perf_counter()
            datas = [await io.read(f"t{int(i)}") for i in idx]
            dt = time.perf_counter() - t0
            dispatches = ec_plan.stats()["dispatches"] - d0
            tier_counters: dict = {}
            for osd in cluster.osds.values():
                for key, v in osd.tier.counters().items():
                    if isinstance(v, int):
                        tier_counters[key] = \
                            tier_counters.get(key, 0) + v
            digest = hash(tuple(bytes(d) for d in datas))
            ok = all(bytes(d) == payloads[int(i)]
                     for d, i in zip(datas, idx))
            return dt, dispatches, tier_counters, digest, ok
        finally:
            await cluster.stop()

    prev = os.environ.get("CEPH_TPU_TIER")
    try:
        os.environ["CEPH_TPU_TIER"] = "1"
        dt_on, disp_on, counters, digest_on, ok_on = \
            asyncio.run(run_mode())
        os.environ["CEPH_TPU_TIER"] = "0"
        dt_off, disp_off, _c, digest_off, ok_off = \
            asyncio.run(run_mode())
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_TIER", None)
        else:
            os.environ["CEPH_TPU_TIER"] = prev
    return {
        "tier_zipf_reads_on_ops_per_sec": n_reads / max(dt_on, 1e-9),
        "tier_zipf_reads_off_ops_per_sec": n_reads / max(dt_off, 1e-9),
        "tier_decode_dispatches_on": disp_on,
        "tier_decode_dispatches_off": disp_off,
        "tier_bytes_identical": bool(ok_on and ok_off
                                     and digest_on == digest_off),
        "tier_counters": counters,
    }


def bench_lrc_crc() -> float:
    """BASELINE config #3: LRC "k=8 m=4 l=4" encode of a 16 MiB blob plus
    crc32c on every 4 KiB block of every chunk (the BlueStore
    _do_alloc_write csum role), on device.

    The kml shorthand cannot express k=8 m=4 l=4 (the reference rejects
    it too: k % ((k+m)/l) != 0, ErasureCodeLrc.cc:334); the reference's
    mechanism for such codes is explicit layers — here 8 data in 2 local
    groups of 4, one local parity each, plus 2 global parities (m=4
    coding chunks, locality 4).  On TPU that whole layered code is ONE
    composite (4x8) GF(2^8) matmul — the Pallas words kernel — and the
    crc32c of all 12 chunks x 4 KiB blocks runs on the SAME word
    layout (crc32c_partial_bits_words); bit-exactness of the composite
    against the layered plugin is asserted before timing.  Timed with
    the same chained-loop differencing as the headline (tunnel RPC
    latency cancels); GiB/s of input data bytes."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.models import reed_solomon as rs
    from ceph_tpu.ops import checksum as cks
    from ceph_tpu.ops import crc_pallas, gf, gf_pallas

    kd, S = 8, 2 << 20  # 8 data chunks x 2 MiB = 16 MiB blob
    csum_block = 4096
    local = rs.reed_sol_van_matrix(4, 1)  # (1, 4) local-parity row
    comp = np.zeros((4, kd), dtype=np.uint8)
    comp[:2] = rs.reed_sol_van_matrix(kd, 2)
    comp[2, :4] = local[0]
    comp[3, 4:] = local[0]

    codec = create_erasure_code({
        "plugin": "lrc",
        "mapping": "DDDDDDDD____",
        "layers": json.dumps([
            ["DDDDDDDDcc__", ""],
            ["DDDD______c_", ""],
            ["____DDDD___c", ""],
        ])})
    rng3 = np.random.default_rng(3)
    blob = rng3.integers(0, 256, kd * S, dtype=np.uint8).tobytes()
    chunks = codec.encode(set(range(12)), blob)
    data1 = np.stack([np.frombuffer(bytes(chunks[i]), dtype=np.uint8)
                      for i in range(kd)])
    par_ref = np.stack([np.frombuffer(bytes(chunks[8 + j]), dtype=np.uint8)
                        for j in range(4)])
    assert np.array_equal(gf.gf_matmul_host(comp, data1), par_ref), \
        "composite LRC matrix != layered plugin output"

    use_pallas = gf_pallas.supported((kd, S))
    consts = cks.make_crc_consts(csum_block)
    comp_key = tuple(tuple(int(c) for c in row) for row in comp)
    gf_pallas.register_matrix(comp)
    words = jax.device_put(jnp.asarray(
        gf_pallas.words_from_bytes(data1[None])))  # (1, 8, R4, 128)
    blocks_per = S // csum_block
    wpb = csum_block // 512  # word-layout rows per csum block

    @functools.partial(jax.jit, static_argnames=("n",))
    def loop_words(dd, n):
        mat = np.array(comp_key, dtype=np.uint8)

        def body(_, carry):
            par = gf_pallas.gf_matmul_words(mat, carry)  # (1,4,R4,128)
            allc = jnp.concatenate([carry, par], axis=1)
            blocks = allc.reshape(12 * blocks_per, wpb * 128)
            crcs = cks.crc32c_pack_bits(
                cks.crc32c_partial_bits_words(blocks, consts))
            fold = (jnp.sum(crcs, dtype=jnp.uint32)
                    & 0xFF).astype(jnp.int32)
            return carry.at[0, 0, 0, 0].set(carry[0, 0, 0, 0] ^ fold)

        return jax.lax.fori_loop(0, n, body, dd).astype(
            jnp.int32).sum()

    @functools.partial(jax.jit, static_argnames=("n",))
    def loop_words_mxu_crc(dd, n):
        # the Pallas crc kernel (ops/crc_pallas.py): per-block crcs as
        # int8 MXU dots straight off the encode kernel's word layout;
        # data and parity blocks are checksummed as separate views so
        # no concat copy rides the hot loop
        mat = np.array(comp_key, dtype=np.uint8)

        def body(_, carry):
            par = gf_pallas.gf_matmul_words(mat, carry)
            dblocks = carry.reshape(kd * blocks_per, wpb * 128)
            pblocks = par.reshape(4 * blocks_per, wpb * 128)
            c1 = crc_pallas.crc32c_blocks_words(dblocks, csum_block)
            c2 = crc_pallas.crc32c_blocks_words(pblocks, csum_block)
            fold = ((jnp.sum(c1, dtype=jnp.uint32)
                     ^ jnp.sum(c2, dtype=jnp.uint32))
                    & 0xFF).astype(jnp.int32)
            return carry.at[0, 0, 0, 0].set(carry[0, 0, 0, 0] ^ fold)

        return jax.lax.fori_loop(0, n, body, dd).astype(
            jnp.int32).sum()

    mbits = jnp.asarray(gf.gf_matrix_to_bits(comp))
    d = jax.device_put(jnp.asarray(data1))

    @functools.partial(jax.jit, static_argnames=("n",))
    def loop(mb, dd, n):
        def body(_, carry):
            par = gf.gf2_matmul_bytes(mb, carry)            # (4, S)
            allc = jnp.concatenate([carry, par], axis=0)    # (12, S)
            blocks = allc.reshape(-1, csum_block)
            crcs = cks.crc32c_pack_bits(
                cks.crc32c_partial_bits(blocks, consts))
            # fold a crc byte into the carry: forces each iteration to
            # depend on the last (serial on device, overlap-free timing)
            fold = (jnp.sum(crcs, dtype=jnp.uint32) & 0xFF).astype(
                jnp.uint8)
            return carry.at[0, 0].set(carry[0, 0] ^ fold)

        return jax.lax.fori_loop(0, n, body, dd).astype(jnp.int32).sum()

    def measure(run, n=41):
        for nn in (1, n):
            run(nn)  # compile + warm

        def t(nn):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                run(nn)
                best = min(best, time.perf_counter() - t0)
            return best

        per_pass = (t(n) - t(1)) / (n - 1)
        return (kd * S) / per_pass / (1 << 30)

    best = measure(lambda nn: float(loop(mbits, d, nn)))
    if use_pallas:
        # correctness of the words formulation vs the host tiers
        par_words = np.asarray(gf_pallas.gf_matmul_words(
            comp, jnp.asarray(gf_pallas.words_from_bytes(data1[None]))))
        got = gf_pallas.bytes_from_words(par_words)[0]
        assert np.array_equal(got, par_ref), "words LRC parity mismatch"
        allc = np.concatenate([data1, par_ref], axis=0)
        want_crcs = [cks.crc32c(0, blk.tobytes())
                     for blk in allc.reshape(-1, csum_block)[:4]]
        words_blocks = jnp.asarray(gf_pallas.words_from_bytes(
            allc)).reshape(12 * blocks_per, wpb * 128)
        got_crcs = np.asarray(cks.crc32c_pack_bits(
            cks.crc32c_partial_bits_words(words_blocks[:4], consts)))
        assert [int(c) for c in got_crcs] == want_crcs, \
            "words crc mismatch"
        # race the formulations and report the winner (what a deployed
        # codec's dispatch would do): XLA bit-planes, words-layout XLA
        # crc, and the Pallas MXU crc kernel
        best = max(best, measure(lambda nn: float(loop_words(words,
                                                             nn))))
        if crc_pallas.supported(csum_block, 12 * blocks_per):
            # bit-exactness of the MXU crc vs the host oracle
            dblocks = jnp.asarray(words).reshape(
                kd * blocks_per, wpb * 128)
            got_mxu = np.asarray(crc_pallas.crc32c_blocks_words(
                dblocks, csum_block, init=0))[:4]
            assert [int(c) for c in got_mxu] == want_crcs, \
                "mxu crc mismatch"
            best = max(best, measure(
                lambda nn: float(loop_words_mxu_crc(words, nn)),
                n=401))
    return best


def bench_put_e2e() -> Tuple[float, float, dict]:
    """BASELINE config #5: 64 MiB multipart PUT into an EC 8+3 pool,
    end to end — host bytes through RGW-lite's processor pipeline, the
    networked rados client, the OSD op engine's EC encode, down to
    durable shards on every OSD store.  Wall-clock GiB/s of object
    bytes.

    Topology: a 12-OSD in-loop cluster (MemStore).  The bench hosts
    are single-core (nproc=1 on the axon TPU VMs), so real daemon
    processes would only add context switches — the in-loop cluster is
    the faster AND the honest shape for this host; the standalone test
    tier covers the multi-process topology for correctness.  Parts
    upload concurrently (stock S3 client behavior); each part's
    stripes pipeline through the processor's aio window.  Same-process
    endpoints ride the messenger's loopback fast path (zero-copy
    message handoff — the AsyncMessenger local-delivery discipline),
    and the datapath is the fused native pass: parity + every crc in
    one cache-resident sweep, data shards adopted by the stores as
    strided views, no transpose or defensive copies
    (native/src/datapath.cc, common/buffer.py, os/memstore.py).

    ETag mode: the gateway runs etag_hash="crc32c" — the deployment
    knob for CPU-constrained hosts (MD5 is a serial ~0.5 GiB/s/core
    hash; S3 itself returns non-MD5 ETags for multipart/SSE-KMS
    objects).  The stock-interop md5 mode is measured alongside and
    reported as put_64MiB_md5_etag_gibs in bench_details.json.

    The per-object EC encode dispatches to the device only when a
    dispatch round-trip is cheap; through a high-latency tunnel the
    codec's host SIMD path wins and the dispatch gate (the
    tpu-min-bytes profile knob) picks it — that choice is part of the
    design and of this number.  bench_details.json records the gate's
    measured inputs (host vs device round-trip seconds)."""
    import asyncio
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from cluster_helpers import Cluster
    from ceph_tpu.rgw import RGWLite

    # pick the codec path honestly: race host SIMD vs device round-trip
    # (incl. transfers + any tunnel latency) on one object-sized probe —
    # the tpu-min-bytes gate's decision, made empirically
    from ceph_tpu.ops import gf as gf_ops
    from ceph_tpu.models import reed_solomon as rs

    mat = rs.reed_sol_van_matrix(8, 3)
    probe = np.random.default_rng(9).integers(
        0, 256, (8, 512 * 1024), dtype=np.uint8)

    def best_of(fn, n=3):
        fn()
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_host = best_of(lambda: gf_ops.gf_matmul_host(mat, probe))
    try:
        t_dev = best_of(lambda: np.asarray(
            gf_ops.gf_matmul_tpu(mat, probe)))
    except Exception:
        t_dev = float("inf")
    use_device = t_dev < t_host
    gate = {"put_gate_host_s": t_host,
            "put_gate_device_s": None if t_dev == float("inf")
            else t_dev,
            "put_encode_backend": "tpu_words" if use_device
            else "host_simd_fused"}

    profile = {"plugin": "ec_jax", "technique": "reed_sol_van",
               "k": "8", "m": "3", "crush-failure-domain": "osd",
               "stripe_unit": "65536",
               "tpu": "true" if use_device else "false"}

    async def run() -> Tuple[float, float]:
        # production-like heartbeat cadence (the reference default is
        # 6s, options.cc osd_heartbeat_interval) — the test tier's
        # 0.3s exists for fast failure-detection tests and on a 1-core
        # host its background pings/placement churn perturb timing
        cluster = Cluster(num_osds=12, osds_per_host=3,
                          osd_config={"osd_heartbeat_interval": 3.0,
                                      "osd_heartbeat_grace": 20.0})
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "rgw.meta", size=3, pg_num=8)
            await cluster.client.create_ec_pool(
                "rgw.data", profile=profile, pg_num=8)
            payload = np.random.default_rng(5).integers(
                0, 256, 64 << 20, dtype=np.uint8).tobytes()
            psize = 16 << 20

            async def put_trials(rgw, tag, n_trials):
                await rgw.create_bucket(f"bench-{tag}")
                best = float("inf")
                for trial in range(n_trials):
                    key = f"obj{trial}"
                    t0 = time.perf_counter()
                    upload = await rgw.init_multipart(f"bench-{tag}",
                                                      key)

                    async def one_part(num):
                        chunk = memoryview(payload)[
                            (num - 1) * psize:num * psize]
                        etag = await rgw.upload_part(
                            f"bench-{tag}", key, upload, num, chunk)
                        return (num, etag)

                    parts = await asyncio.gather(
                        *(one_part(n) for n in range(1, 5)))
                    await rgw.complete_multipart(
                        f"bench-{tag}", key, upload, list(parts))
                    dt = time.perf_counter() - t0
                    if trial > 0:   # first trial warms connections
                        best = min(best, dt)
                # integrity: the bytes made it back out
                got = await rgw.get_object(f"bench-{tag}", "obj1")
                assert got == payload
                return len(payload) / best / (1 << 30)

            # 16 MiB stripes (a deployment knob, rgw_obj_stripe_size):
            # on a single-core host, per-message overhead is the
            # budget, so fewer+larger rados objects win
            fast = await put_trials(
                RGWLite(cluster.client, "rgw.data", "rgw.meta",
                        stripe_size=16 << 20, etag_hash="crc32c"),
                "crc", 6)
            md5 = await put_trials(
                RGWLite(cluster.client, "rgw.data", "rgw.meta",
                        stripe_size=16 << 20), "md5", 3)
            return fast, md5
        finally:
            await cluster.stop()

    fast, md5 = asyncio.run(run())
    return fast, md5, gate


def main() -> None:
    stall = float(os.environ.get("CEPH_TPU_BENCH_STALL_S", "0") or 0)
    if stall > 0:
        # test seam for the contract watchdog: simulate a MANDATORY
        # stage wedging pre-contract (the BENCH_r05 failure shape)
        time.sleep(stall)
    import jax
    import jax.numpy as jnp

    from ceph_tpu.models import reed_solomon as rs
    from ceph_tpu.ops import gf, gf_pallas
    from ceph_tpu import native

    k, m = 8, 3
    if _SMOKE:
        chunk, batch = 4096, 2
    else:
        chunk = 512 * 1024      # 4 MiB stripe = k * 512 KiB
        batch = 16              # stripes per dispatch (64 MiB data)
    matrix = rs.reed_sol_van_matrix(k, m)
    gf_pallas.register_matrix(matrix)  # what ec_jax init() does
    mbits = jnp.asarray(gf.gf_matrix_to_bits(matrix))

    rng = np.random.default_rng(0)
    data_host = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)

    # plan-cache probe: one miss (compile) + one hit on the same
    # bucket, correctness vs the host oracle — the counters land in
    # the contract line so the driver sees the cache working
    from ceph_tpu.ec import plan as ec_plan

    ec_plan.reset_stats()
    demo = data_host[:2, :, :4096]
    par1 = ec_plan.encode(matrix, demo, sig="bench-demo")
    par2 = ec_plan.encode(matrix, demo, sig="bench-demo")
    assert par1 is not None and np.array_equal(par1, par2)
    assert np.array_equal(par1[0], gf.gf_matmul_host(matrix, demo[0])), \
        "plan-cached parity != host oracle"

    data = jax.device_put(jnp.asarray(data_host))
    data_bytes = batch * k * chunk
    use_pallas = gf_pallas.supported((batch, k, chunk))
    # device-native word layout (free view of the same bytes on host)
    words = jax.device_put(jnp.asarray(
        gf_pallas.words_from_bytes(data_host))) if use_pallas else None

    # integrity: the Pallas kernel's parity is bit-exact vs the host SIMD
    # oracle before any timing
    if use_pallas:
        got = gf_pallas.gf_matmul_pallas(matrix, data_host[:2])
        want = np.stack([gf.gf_matmul_host(matrix, data_host[i])
                         for i in range(2)])
        assert np.array_equal(got, want), "pallas parity != host oracle"

    @functools.partial(jax.jit, static_argnames=("n", "rows"))
    def loop(mb, d, n, rows):
        # data-dependent chain of encodes; scalar out forces completion
        def body(_, carry):
            p = gf.gf2_matmul_bytes(mb, carry)
            return carry.at[:, :rows, :].set(p)

        return jax.lax.fori_loop(0, n, body, d).astype(jnp.int32).sum()

    @functools.partial(jax.jit, static_argnames=("mat_key", "n", "rows"))
    def loop_words(d, mat_key, n, rows):
        mat = np.array(mat_key, dtype=np.uint8)
        def body(_, carry):
            p = gf_pallas.gf_matmul_words(mat, carry)
            return carry.at[:, :rows].set(p)

        return jax.lax.fori_loop(0, n, body, d).astype(jnp.int32).sum()

    def differenced(run, n, iters=5):
        for nn in (1, n):
            float(run(nn))  # compile + warm
        def t(nn):
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                float(run(nn))
                best = min(best, time.perf_counter() - t0)
            return best
        return (t(n) - t(1)) / (n - 1)

    def device_seconds_per_encode(mb, d, rows, n=201, iters=5):
        if _SMOKE:
            n, iters = 3, 1
        return differenced(lambda nn: loop(mb, d, nn, rows), n, iters)

    def words_seconds(mat, d, rows, n=801, iters=5):
        if _SMOKE:
            n, iters = 3, 1
        key = tuple(tuple(int(c) for c in row) for row in mat)
        return differenced(lambda nn: loop_words(d, key, nn, rows), n, iters)

    enc_xla_gibs = None
    if use_pallas:
        t_enc = words_seconds(matrix, words, rows=m)
        enc_gibs = data_bytes / t_enc / (1 << 30)
        t_xla = device_seconds_per_encode(mbits, data, rows=m)
        enc_xla_gibs = data_bytes / t_xla / (1 << 30)
    else:
        t_enc = device_seconds_per_encode(mbits, data, rows=m)
        enc_gibs = data_bytes / t_enc / (1 << 30)

    decode_sweep = {}
    dec_gibs = None

    # CPU baseline: native SIMD GF matmul (AVX2/SSSE3 split-table
    # shuffle, gf_simd.cc — the jerasure-SSE/isa-l speed tier), one
    # stripe, single thread like ceph_erasure_code_benchmark.  Runs
    # BEFORE the decode sweep so the driver contract line (which needs
    # vs_baseline) goes out ahead of every optional bench.  Smoke mode
    # skips it: native.get_lib() may lazily build the C++ extension.
    lib = None if _SMOKE else native.get_lib()
    cpu_gibs = cpu_scalar_gibs = None
    simd_level = None
    cpu_k4m2_gibs = None
    if lib is not None:
        import ctypes

        u8p = ctypes.POINTER(ctypes.c_uint8)

        def cpu_bench(fn, kk, mm, size, iters=5, mat=None):
            if mat is None:
                mat = rs.reed_sol_van_matrix(kk, mm)
            tables = np.ascontiguousarray(gf.gf_mul_tables(mat))
            src = np.ascontiguousarray(
                rng.integers(0, 256, (kk, size), dtype=np.uint8))
            out = np.zeros((mm, size), dtype=np.uint8)

            def once():
                fn(tables.ctypes.data_as(u8p), mm, kk,
                   src.ctypes.data_as(u8p), size,
                   out.ctypes.data_as(u8p))

            once()
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                once()
                best = min(best, time.perf_counter() - t0)
            return (kk * size) / best / (1 << 30)

        have_simd = hasattr(lib, "ceph_tpu_gf_matmul_simd")
        if have_simd:
            simd_level = lib.ceph_tpu_gf_simd_level()
            cpu_gibs = cpu_bench(lib.ceph_tpu_gf_matmul_simd, k, m, chunk)
            # BASELINE config #1 shape: k=4 m=2, 1 MiB objects
            cpu_k4m2_gibs = cpu_bench(lib.ceph_tpu_gf_matmul_simd,
                                      4, 2, (1 << 20) // 4)
            # decode sweep, CPU SIMD tier (same matrices as the TPU sweep)
            for e in range(1, m + 1):
                dmat = rs.decode_matrix(
                    matrix, k, list(range(e)),
                    list(range(e, k)) + list(range(k, k + e)))
                decode_sweep[f"cpu_decode_{e}_erasure_gibs"] = cpu_bench(
                    lib.ceph_tpu_gf_matmul_simd, k, e, chunk, mat=dmat)
        cpu_scalar_gibs = cpu_bench(lib.ceph_tpu_gf_matmul, k, m, chunk)
        if cpu_gibs is None:
            cpu_gibs = cpu_scalar_gibs

    # None (JSON null) when no native CPU baseline could be measured here —
    # distinguishable from a measured ratio of exactly 1.0
    vs_baseline = (enc_gibs / cpu_gibs) if cpu_gibs else None

    # budget decision, made ONCE here so the contract's `truncated`
    # flag matches what actually runs: when the remaining wall clock
    # cannot cover the optional sections, skip them all
    reserve = float(os.environ.get("CEPH_TPU_BENCH_RESERVE", "300"))
    skip_optional = _remaining() < reserve
    skipped_sections = []
    ps = ec_plan.stats()
    plan_counters = {key: ps[key] for key in ("hits", "misses",
                                              "retraces")}
    # encode-service probe (cheap, before the contract): concurrent
    # awaited encodes bit-exact vs inline, counters into the contract
    service_counters = _service_probe()
    # hot-set/read-tier probe (cheap, before the contract):
    # device-batched bloom bit-exact + agent promote/hit/evict alive
    tier_counters = _tier_probe()
    # device-fault probe (cheap, before the contract): forced device
    # failure degrades bit-exactly to host, breaker trips and recovers
    device_health_counters = _device_health_probe()
    # hedged-read probe (cheap, before the contract): first-k
    # completion under an injected straggler, cancellation-clean
    tail_counters = _hedge_probe()
    # open-loop load probe (cheap, before the contract): hundreds to
    # a thousand tenants over the embedded cluster, goodput +
    # streaming percentiles, deterministic schedules
    load_counters = _load_probe()
    # crash-consistency probe (cheap, before the contract): smoke
    # power-cut sweep with zero violations + broken-store self-test
    durability_counters = _durability_probe()
    # mesh probe (before the contract): 1-dev/N-dev/host bit-exact,
    # sick chip shrinks the mesh with zero host fallbacks
    mesh_counters = _mesh_probe()
    # multihost probe (before the contract): bit-exact encode across
    # a real 2-process jax.distributed group + the host-loss leg
    # (one host event, one shrink, zero host fallbacks)
    multihost_counters = _multihost_probe()
    # spmd collective-safety probe (before the contract): static
    # collective-site map non-empty, the 2-process leg's runtime
    # trace ⊆ static map, per-process order congruence
    spmd_counters = _spmd_probe(multihost_counters)
    # critical-path tracing probe (before the contract): reducer
    # reconstructs a hand-built tree, spans-on-vs-off overhead at
    # sample rate 0 through a live loopback cluster
    trace_counters = _trace_probe()
    # group-commit probe (before the contract): N concurrent durable
    # writes share barriers (fsyncs < N), bit-exact, kill switch pays
    # one commit per txn
    group_commit_counters = _group_commit_probe()
    # coded-compute probe (before the contract): tiny scan bit-exact
    # through first-k result-domain decode + the hedged straggler leg
    compute_counters = _compute_probe()
    # codec-compiler probe (before the contract): compiled XOR
    # schedules bit-exact vs the naive row-walk across the bitmatrix
    # family, with the measured XOR-count reduction + memo hits
    xsched_counters = _xsched_probe()
    # MSR regenerating-codec probe (before the contract): every
    # single-erasure pattern rebuilt bit-exact from d beta-fragments,
    # fragment bytes on the product-matrix bound (0.5x the k-read)
    repair_counters = _repair_probe()
    # coded-inference probe (before the contract): Fisher-fused
    # serving streams bit-exact on the full set, every single-shard
    # loss within the error budget, and the hedged straggler leg
    # first-sufficient without the slow stream
    inference_counters = _inference_probe()
    # compound-chaos probe (before the contract): a seeded composed
    # 3-hazard scenario over live traffic, every invariant monitor
    # armed, violations=0 and the seed echoed for replay
    chaos_counters = _chaos_probe()

    # the driver contract line, before every optional/extended bench:
    # a wedge below this point can cost detail rows, never the bench
    _emit_contract(enc_gibs, vs_baseline, plan_cache=plan_counters,
                   encode_service=service_counters,
                   tier=tier_counters,
                   device_health=device_health_counters,
                   tail=tail_counters,
                   load=load_counters,
                   durability=durability_counters,
                   mesh=mesh_counters,
                   multihost=multihost_counters,
                   trace=trace_counters,
                   group_commit=group_commit_counters,
                   compute=compute_counters,
                   xsched=xsched_counters,
                   spmd=spmd_counters,
                   repair=repair_counters,
                   inference=inference_counters,
                   chaos=chaos_counters,
                   truncated=skip_optional)

    # decode sweep over 1..m erasures (the reference benchmark sweeps
    # erasure counts: ceph_erasure_code_benchmark.cc:251-317).  Lost
    # chunks 0..e-1 rebuilt from k survivors; the production decode path
    # is the generic SMEM-coefficient kernel (unregistered matrices).
    if skip_optional:
        skipped_sections.append("decode_sweep")
    else:
        for e in range(1, m + 1):
            lost = list(range(e))
            have = list(range(e, k)) + list(range(k, k + e))
            dmat = rs.decode_matrix(matrix, k, lost, have)
            if use_pallas:
                t_d = words_seconds(dmat, words, rows=e)
            else:
                dmb = jnp.asarray(gf.gf_matrix_to_bits(dmat))
                t_d = device_seconds_per_encode(dmb, data, rows=e)
            decode_sweep[f"decode_{e}_erasure_gibs"] = (
                data_bytes / t_d / (1 << 30))
            if e == 1:
                dec_gibs = decode_sweep["decode_1_erasure_gibs"]

    # BASELINE config #3: LRC k=8 m=4 l=4 encode + crc32c over a 16 MiB
    # BlueStore-style blob, wall-clock end to end (host bytes in, chunks +
    # per-4KiB-block checksums out)
    lrc_gibs = None
    if skip_optional and not _SMOKE:
        skipped_sections.append("lrc")
    if not _SMOKE and not skip_optional:
        try:
            lrc_gibs = bench_lrc_crc()
        except Exception as e:  # report the row as absent, not a crash
            print(f"# lrc bench failed: {e!r}", file=sys.stderr)

    # BASELINE config #5: end-to-end 64 MiB multipart PUT (RGW-lite ->
    # rados -> OSD EC encode -> durable shards).  Governed by the same
    # single decision as the other optional sections, so the contract
    # line's `truncated` flag always matches what ran.
    put_gibs = put_md5_gibs = None
    put_gate = {}
    if not _SMOKE and skip_optional:
        skipped_sections.append("put_e2e")
    elif not _SMOKE:
        try:
            put_gibs, put_md5_gibs, put_gate = bench_put_e2e()
        except Exception as e:
            print(f"# put e2e bench failed: {e!r}", file=sys.stderr)

    # write-path section: concurrent client writes through the OSD op
    # engine, micro-batching encode service on vs off (same single
    # budget decision as the other optional sections)
    write_path: dict = {}
    if not _SMOKE and skip_optional:
        skipped_sections.append("write_path")
    elif not _SMOKE:
        try:
            write_path = bench_write_path()
        except Exception as e:
            print(f"# write path bench failed: {e!r}", file=sys.stderr)

    # tier section: skewed-read leg through a live cluster, read tier
    # on vs off, decode-dispatch delta from plan.stats()
    tier_section: dict = {}
    if not _SMOKE and skip_optional:
        skipped_sections.append("tier")
    elif not _SMOKE:
        try:
            tier_section = bench_tier()
        except Exception as e:
            print(f"# tier bench failed: {e!r}", file=sys.stderr)

    # tail-latency section: EC reads under one injected slow OSD,
    # hedging on vs off, p50/p95/p99 + the p99 improvement multiple
    tail_section: dict = {}
    if skip_optional:
        skipped_sections.append("tail")
    else:
        try:
            tail_section = bench_tail()
        except Exception as e:
            print(f"# tail bench failed: {e!r}", file=sys.stderr)

    # mesh scale-out section: the fused encode+crc sweep at mesh
    # sizes 1 -> 2 -> 4 -> 8 — GiB/s per size, speedup over the
    # single-chip leg, bit-exact at every size
    mesh_section: dict = {}
    if skip_optional:
        skipped_sections.append("mesh")
    else:
        try:
            mesh_section = bench_mesh()
        except Exception as e:
            print(f"# mesh bench failed: {e!r}", file=sys.stderr)

    # cross-host scale-out section: the --processes sweep axis (real
    # jax.distributed process groups) + the host-loss shrink leg
    multihost_section: dict = {}
    if skip_optional:
        skipped_sections.append("multihost")
    else:
        try:
            multihost_section = bench_multihost()
        except Exception as e:
            print(f"# multihost bench failed: {e!r}",
                  file=sys.stderr)

    # per-stage latency decomposition under load: concurrent EC R/W
    # clients, then the OSDs' critical-path stage histograms roll up
    # into stage p50/p99 self-times
    trace_section: dict = {}
    if skip_optional:
        skipped_sections.append("trace")
    else:
        try:
            trace_section = bench_trace()
        except Exception as e:
            print(f"# trace bench failed: {e!r}", file=sys.stderr)

    # group-commit section: p50/p99 write latency with the TPUStore
    # commit lane on vs off, journal-stage self-times per mode, and
    # the fsyncs-per-N-writers barrier counters
    group_commit_section: dict = {}
    if skip_optional:
        skipped_sections.append("group_commit")
    else:
        try:
            group_commit_section = bench_group_commit()
        except Exception as e:
            print(f"# group commit bench failed: {e!r}",
                  file=sys.stderr)

    # coded-compute section: the scan-N-objects leg — pushdown vs
    # client-side read-then-compute wall-clock, bytes moved per mode,
    # straggler flatness, per-stage compute decomposition
    compute_section: dict = {}
    if skip_optional:
        skipped_sections.append("compute")
    else:
        try:
            compute_section = bench_compute()
        except Exception as e:
            print(f"# compute bench failed: {e!r}", file=sys.stderr)

    # coded-inference section: the serve-through-the-code leg —
    # coded approx vs exact vs read-then-infer wall-clock and bytes,
    # accuracy delta vs the budget, kill-switch parity, straggler
    # p99 flatness, per-stage infer decomposition
    inference_section: dict = {}
    if skip_optional:
        skipped_sections.append("inference")
    else:
        try:
            inference_section = bench_inference()
        except Exception as e:
            print(f"# inference bench failed: {e!r}", file=sys.stderr)

    # codec-compiler section: the small-chunk scheduled-vs-naive
    # sweep (encode AND decode) + the live-cluster leg citing the
    # encode_wait stage histogram per mode
    xsched_section: dict = {}
    if skip_optional:
        skipped_sections.append("xsched")
    else:
        try:
            xsched_section = bench_xsched()
        except Exception as e:
            print(f"# xsched bench failed: {e!r}", file=sys.stderr)

    # small-op band section: 4 KiB objects through a live EC cluster
    # under open-loop load — ops/s + p99 with the native fused
    # executor + sub-chunk fast lane on vs off, the win named per
    # stage and attributed via the native/tape counters
    smallop_section: dict = {}
    if skip_optional:
        skipped_sections.append("smallop")
    else:
        try:
            smallop_section = bench_smallop()
        except Exception as e:
            print(f"# smallop bench failed: {e!r}", file=sys.stderr)

    # degraded-mode section: breakers forced open -> host-path
    # throughput delta (what a wedged accelerator costs while the
    # breaker holds it out of the hot path)
    degraded_section: dict = {}
    if skip_optional:
        skipped_sections.append("degraded")
    else:
        try:
            degraded_section = bench_degraded()
        except Exception as e:
            print(f"# degraded bench failed: {e!r}", file=sys.stderr)

    # repair-bandwidth section: live MSR pool loses an OSD, the
    # repair-aware recovery's bytes-read-per-repaired-byte + wall
    # clock vs the CEPH_TPU_MSR_REPAIR=0 classic k-read baseline
    repair_section: dict = {}
    if skip_optional:
        skipped_sections.append("repair")
    else:
        try:
            repair_section = bench_repair()
        except Exception as e:
            print(f"# repair bench failed: {e!r}", file=sys.stderr)

    # open-loop load sweep: the same tenant population at doubling
    # arrival rates until the knee (goodput stops tracking offered)
    load_section: dict = {}
    if skip_optional:
        skipped_sections.append("load")
    else:
        try:
            load_section = bench_load()
        except Exception as e:
            print(f"# load bench failed: {e!r}", file=sys.stderr)

    # full crash sweep: the acceptance-sized power-cut exploration
    # (every cut/schedule + double-crash legs), zero violations
    durability_section: dict = {}
    if _SMOKE:
        pass  # the pre-contract probe already swept smoke-sized
    elif skip_optional:
        skipped_sections.append("durability")
    else:
        try:
            durability_section = bench_durability()
        except Exception as e:
            print(f"# durability bench failed: {e!r}", file=sys.stderr)

    # QoS isolation proof: tenant B's p99 across tenant A's 1x->10x
    # step, per-tenant mClock + admission gate on vs off.  Live
    # clusters x4: out of smoke mode (the scheduler-level isolation
    # regression lives in the test tier)
    qos_section: dict = {}
    if _SMOKE:
        pass
    elif skip_optional:
        skipped_sections.append("qos")
    else:
        try:
            qos_section = bench_qos()
        except Exception as e:
            print(f"# qos bench failed: {e!r}", file=sys.stderr)

    # compound-chaos section: the full six-hazard matrix over a
    # persistent cluster with zero tolerated violations, the dmClock
    # delta/rho on/off legs, and the worst-op trace exemplar.  Live
    # clusters x3: out of smoke mode (the composed-matrix regression
    # lives in the test tier's slow leg)
    chaos_section: dict = {}
    if _SMOKE:
        pass
    elif skip_optional:
        skipped_sections.append("chaos")
    else:
        try:
            chaos_section = bench_chaos()
        except Exception as e:
            print(f"# chaos bench failed: {e!r}", file=sys.stderr)

    details = {
        "encode_gibs": enc_gibs,
        "encode_path": "pallas_words" if use_pallas else "xla_bitplanes",
        "encode_xla_gibs": enc_xla_gibs,
        "decode_single_erasure_gibs": dec_gibs,
        **decode_sweep,
        "cpu_native_gibs": cpu_gibs,
        "cpu_scalar_gibs": cpu_scalar_gibs,
        "cpu_simd_level": simd_level,
        "cpu_simd_k4m2_1MiB_gibs": cpu_k4m2_gibs,
        "lrc_k8m4l4_crc32c_16MiB_gibs": lrc_gibs,
        "put_64MiB_ec8p3_gibs": put_gibs,
        "put_64MiB_md5_etag_gibs": put_md5_gibs,
        **put_gate,
        **write_path,
        **tier_section,
        **tail_section,
        **trace_section,
        **group_commit_section,
        **mesh_section,
        **multihost_section,
        **compute_section,
        **inference_section,
        **xsched_section,
        **smallop_section,
        **degraded_section,
        **repair_section,
        **load_section,
        **durability_section,
        **qos_section,
        **chaos_section,
        "encode_service": service_counters,
        "tier": tier_counters,
        "device_health": device_health_counters,
        "tail": tail_counters,
        "load": load_counters,
        "durability": durability_counters,
        "mesh": mesh_counters,
        "multihost": multihost_counters,
        "trace": trace_counters,
        "group_commit": group_commit_counters,
        "compute": compute_counters,
        "xsched": xsched_counters,
        "repair": repair_counters,
        "inference": inference_counters,
        "chaos": chaos_counters,
        "host_cores": os.cpu_count(),
        "encode_ms_per_batch": t_enc * 1e3,
        "k": k, "m": m, "chunk_bytes": chunk, "batch": batch,
        "backend": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "plan_cache": ec_plan.stats(),
        "budget_seconds": _budget_seconds(),
        "elapsed_seconds": time.monotonic() - _T0,
        "truncated": bool(skipped_sections),
        "skipped_sections": skipped_sections,
    }
    with open("bench_details.json", "w") as f:
        json.dump(details, f, indent=2)


def _probe_backend(timeout_s: Optional[float] = None) -> Optional[str]:
    """Probe jax backend init in a SUBPROCESS under a hard timeout:
    jax memoizes backend-init failures (an in-process probe would
    poison this process's later init), and a wedged TPU tunnel can
    hang jax.devices() forever — the timeout contains that to the
    child.  Returns the platform string, or None (init failed/hung).

    Test hooks: CEPH_TPU_BENCH_PROBE overrides the probe source,
    CEPH_TPU_BENCH_PROBE_TIMEOUT the per-attempt timeout seconds."""
    src = os.environ.get(
        "CEPH_TPU_BENCH_PROBE",
        "import jax; print(jax.devices()[0].platform)")
    if timeout_s is None:
        timeout_s = float(os.environ.get(
            "CEPH_TPU_BENCH_PROBE_TIMEOUT", "90"))
    try:
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    return lines[-1] if lines else "unknown"


def _ensure_backend() -> str:
    """Wait briefly for a flaky tunnel, then FALL BACK to the host CPU
    tier rather than hang: a degraded number beats a dead round (the
    BENCH_r05 rc=124 failure mode).  Returns the platform the bench
    will run on."""
    attempts = int(os.environ.get("CEPH_TPU_BENCH_PROBE_ATTEMPTS", "3"))
    retry_sleep = float(os.environ.get(
        "CEPH_TPU_BENCH_PROBE_RETRY_SLEEP", "20"))
    for i in range(attempts):
        platform = _probe_backend()
        if platform is not None:
            return platform
        if i < attempts - 1:
            time.sleep(retry_sleep)
    print("# backend probe failed/hung; falling back to CPU tier",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:  # if jax is already imported (preload .pth hook), pin it too
        if "jax" in sys.modules:
            sys.modules["jax"].config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return "cpu"


def cli() -> int:
    """Entry point with the first-and-always contract guarantee: the
    one JSON line goes out even when the bench itself dies — and,
    via the deadline watchdog, even when it WEDGES (the BENCH_r05
    rc=124 shape: the outer harness timeout kills the process, but
    the truncated contract line is already flushed)."""
    watchdog = _arm_contract_watchdog()
    backend = _ensure_backend()
    try:
        main()
    except BaseException as e:
        # null value = no measurement this round; the line itself (the
        # driver contract) still goes out, details on stderr
        _emit_contract(None, None, truncated=_remaining() < 0)
        print(f"# bench failed on backend {backend!r}: {e!r}",
              file=sys.stderr)
        if isinstance(e, KeyboardInterrupt):
            raise
    finally:
        watchdog.cancel()
    return 0


if __name__ == "__main__":
    sys.exit(cli())

"""Paxos + elections for the multi-monitor control plane.

Reference parity: /root/reference/src/mon/Paxos.cc (collect/last/begin/
accept/commit/lease state machine, PN = (n/100+1)*100+rank, one in-flight
proposal, peon catch-up by sharing committed values),
/root/reference/src/mon/ElectionLogic.cc + Elector.cc (epoch-numbered
elections, lowest rank in the connected majority wins, victory broadcast),
re-designed for this framework's asyncio messenger.

Shape notes (where this deliberately differs from the reference, for
honesty):
- One Paxos instance carries one value stream (OSDMap incrementals);
  the reference multiplexes several PaxosServices over one Paxos.
- Peons serve OSDMap reads from committed state regardless of lease —
  epochs are monotonic and every consumer already handles staleness by
  pulling ranges; the lease's load-bearing role here is leader liveness
  (a peon whose lease expires calls an election), matching the
  reference's failure-detection effect if not its read gating.
- Committed values ship inside the COMMIT message (the reference also
  does this for peons that missed the BEGIN).

Durability: every accept/commit writes through the mon's KeyValueDB in
the same transaction as the map it produces (MonitorDBStore discipline);
an in-memory dict stands in when the mon runs storeless (unit tests).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Set

from ceph_tpu.common import lockdep
from ceph_tpu.msg.messages import MMonElection, MMonPaxos

log = logging.getLogger("mon.paxos")

# MMonElection kinds
E_PROPOSE = 1
E_ACK = 2
E_VICTORY = 3
E_PING = 4
E_PONG = 5

# mon_election_default_strategy values (ElectionLogic.h)
STRATEGY_CLASSIC = 1
STRATEGY_CONNECTIVITY = 3

# MMonPaxos ops (Paxos.h op names)
OP_COLLECT = 1
OP_LAST = 2
OP_BEGIN = 3
OP_ACCEPT = 4
OP_COMMIT = 5
OP_LEASE = 6
OP_PULL = 7   # peon asks leader for committed values it missed
OP_FULL = 8   # leader ships a full-state snapshot past a trimmed log

DEFAULTS = {
    "mon_lease": 2.0,
    "mon_lease_renew_interval_factor": 0.4,
    "mon_election_timeout": 2.5,
    "mon_accept_timeout": 2.0,
    "paxos_max_log": 1024,
    "mon_election_default_strategy": STRATEGY_CLASSIC,
    "mon_elector_ping_interval": 0.4,
    "mon_elector_score_halflife": 4.0,
    "mon_elector_ignore_propose_margin": 0.05,
}


class ConnectionTracker:
    """Peer-reachability scores for CONNECTIVITY elections.

    Reference parity: /root/reference/src/mon/ConnectionTracker.cc —
    each mon scores every peer by the fraction of recent ping epochs it
    answered, decayed with a half-life so old history fades.  The
    reference gossips full per-peer report blobs and averages everyone's
    view of a candidate; here each mon keeps its own view and candidates
    self-report one aggregate in the PROPOSE message — the two views are
    averaged at the voter (same signal, one float on the wire).
    """

    def __init__(self, half_life: float = 4.0):
        self.half_life = max(0.1, float(half_life))
        # peer -> [score, last_report_monotonic]; unseen peers score 1.0
        # (a freshly-booted quorum must be electable before any pings)
        self._scores: Dict[int, List[float]] = {}

    def report(self, peer: int, ok: bool,
               now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        ent = self._scores.get(peer)
        if ent is None:
            ent = [1.0, now]
            self._scores[peer] = ent
        score, last = ent
        # decay the old estimate toward this observation: weight halves
        # every half_life seconds of elapsed time (so a peer that stops
        # answering slides to 0 at a rate set by config, not ping count)
        w = 0.5 ** (max(0.0, now - last) / self.half_life)
        ent[0] = score * w + (0.0 + ok) * (1.0 - w)
        ent[1] = now

    def score(self, peer: int) -> float:
        ent = self._scores.get(peer)
        return 1.0 if ent is None else ent[0]

    def my_score(self, n: int, me: int) -> float:
        """Aggregate: mean reachability of every OTHER mon from here —
        a mon with lossy links sees low scores everywhere, so its own
        candidacy self-reports weak (get_total_connection_score role)."""
        others = [self.score(p) for p in range(n) if p != me]
        return sum(others) / len(others) if others else 1.0

    def best_link(self, n: int, me: int) -> float:
        """Max peer score: distinguishes 'I am healthy, THAT peer is
        lossy' (max ~1: some link is solid) from 'MY links are lossy'
        (max low: every view is degraded).  The mean cannot tell the
        two apart — both drag it down."""
        others = [self.score(p) for p in range(n) if p != me]
        return max(others) if others else 1.0


class MemStore:
    """Dict-shaped stand-in for the KeyValueDB when the mon is
    storeless; same get/transaction surface the mon uses."""

    def __init__(self) -> None:
        self.kv: Dict[tuple, bytes] = {}

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        return self.kv.get((table, bytes(key)))

    def get_iterator(self, table: str):
        return sorted((k[1], v) for k, v in self.kv.items()
                      if k[0] == table)

    class _Txn:
        def __init__(self, kv):
            self.kv = kv
            self.ops: List = []

        def set(self, table, key, val):
            self.ops.append(("set", table, bytes(key), bytes(val)))

        def rm_range_keys(self, table, lo, hi):
            self.ops.append(("rm_range", table, bytes(lo), bytes(hi)))

    def get_transaction(self):
        return self._Txn(self.kv)

    def submit_transaction_sync(self, t) -> None:
        for op in t.ops:
            if op[0] == "set":
                self.kv[(op[1], op[2])] = op[3]
            else:
                _tag, table, lo, hi = op
                for k in [k for k in self.kv
                          if k[0] == table and lo <= k[1] < hi]:
                    del self.kv[k]


class Elector:
    """Elections (ElectionLogic.cc strategies): CLASSIC — the lowest
    rank a majority can reach wins; CONNECTIVITY — candidates carry a
    reachability score and voters defer to the best-connected candidate
    (rank only breaks ties), so a flapping low-rank mon stops winning."""

    def __init__(self, rank: int, n: int,
                 send: Callable[[int, Any], Awaitable[None]],
                 on_win: Callable[[int, Set[int]], Awaitable[None]],
                 on_lose: Callable[[int, int], Awaitable[None]],
                 config: Dict[str, Any]):
        self.rank = rank
        self.n = n
        self.send = send
        self.on_win = on_win      # (epoch, quorum)
        self.on_lose = on_lose    # (epoch, leader)
        self.config = config
        self.epoch = 0            # persisted by the mon across restarts
        self.leader: Optional[int] = None
        self.quorum: Set[int] = set()
        self.electing = False
        self._acks: Set[int] = set()
        self._timer: Optional[asyncio.Task] = None
        # single promise per epoch: (epoch, rank) last acked — without
        # this, two proposers can both assemble a majority in the same
        # epoch (the split-vote a promise rules out)
        self._promised: tuple = (0, -1)
        self.strategy = int(config.get(
            "mon_election_default_strategy", STRATEGY_CLASSIC))
        self.tracker = ConnectionTracker(float(config.get(
            "mon_elector_score_halflife", 4.0)))
        self._ping_task: Optional[asyncio.Task] = None
        self._pong_pending: Set[int] = set()
        # boot grace: a peer still booting (messenger bound, elector
        # not yet dispatching) must not poison the tracker before it
        # had a chance to answer — but only for a bounded time, or a
        # permanently dark peer would keep a perfect score forever
        self._ever_ponged: Set[int] = set()
        self._first_ping: Dict[int, float] = {}
        self._last_dethrone = 0.0
        # retained: asyncio holds tasks weakly — an unreferenced
        # dethrone election could be GC'd mid-flight
        self._dethrone_task: Optional[asyncio.Task] = None

    @property
    def majority(self) -> int:
        return self.n // 2 + 1

    def _margin(self) -> float:
        return float(self.config.get(
            "mon_elector_ignore_propose_margin", 0.05))

    def my_score(self) -> float:
        return self.tracker.my_score(self.n, self.rank)

    def _should_defer(self, msg: Any) -> bool:
        """CONNECTIVITY vote: defer to a candidate that is better
        connected than me (averaging its self-report with my own view of
        it — the reference averages every mon's report); within the
        margin, fall back to rank priority so equal-health quorums still
        converge on rank like CLASSIC."""
        if self.strategy != STRATEGY_CONNECTIVITY:
            return msg.rank < self.rank
        cand = (msg.score + self.tracker.score(msg.rank)) / 2.0
        mine = self.my_score()
        m = self._margin()
        if cand > mine + m:
            return True
        if cand < mine - m:
            return False
        return msg.rank < self.rank

    async def start(self) -> None:
        if self.strategy == STRATEGY_CONNECTIVITY and self.n > 1:
            self._ping_task = asyncio.get_running_loop().create_task(
                self._ping_loop())
        await self.call_election()

    async def _ping_loop(self) -> None:
        """Mon-to-mon liveness probes feeding the tracker (Elector's
        send_peer_ping/begin_peer_ping role): a peer that misses the
        round-trip by the next cycle scores a failure.  Probes run
        concurrently under a timeout — a blackholed peer (dropped-SYN
        partition, the very case CONNECTIVITY exists for) must not
        stall the other peers' probes behind its TCP connect."""
        interval = float(self.config.get(
            "mon_elector_ping_interval", 0.4))
        boot_grace = float(self.config.get(
            "mon_election_timeout", 2.5))

        async def probe(peer: int) -> None:
            try:
                await asyncio.wait_for(
                    self.send(peer, MMonElection(
                        E_PING, self.epoch, self.rank)),
                    timeout=max(interval, 0.1))
            except Exception:
                pass  # the missed pong is the signal

        while True:
            now = time.monotonic()
            for peer in self._pong_pending:
                if peer in self._ever_ponged or \
                        now - self._first_ping.get(peer, now) \
                        > boot_grace:
                    self.tracker.report(peer, False)
            self._pong_pending = {p for p in range(self.n)
                                  if p != self.rank}
            for peer in self._pong_pending:
                self._first_ping.setdefault(peer, now)
            await asyncio.gather(*(probe(p)
                                   for p in self._pong_pending))
            self._maybe_dethrone(now)
            await asyncio.sleep(interval)

    def _maybe_dethrone(self, now: float) -> None:
        """Scores are otherwise only consulted at election time — a
        sitting leader whose links collapse would reign as long as the
        odd lease squeaks through.  A peon dethrones only on ABSOLUTE
        evidence: the leader's link to me has collapsed (score below
        the bar) AND I hold at least one solid link (a mon whose OWN
        links are lossy sees everyone low, including the leader — a
        relative mine-vs-leader comparison would let the flapping node
        itself thrash elections).  Rate-limited to one per election
        timeout so a borderline score can't thrash either."""
        if self.electing or self.leader is None or \
                self.leader == self.rank:
            return
        cooldown = float(self.config.get("mon_election_timeout", 2.5))
        if now - self._last_dethrone < cooldown:
            return
        lead = self.tracker.score(self.leader)
        best = self.tracker.best_link(self.n, self.rank)
        if lead < 0.5 and best >= 0.75:
            self._last_dethrone = now
            log.warning("mon.%d: leader mon.%d connectivity score %.2f"
                        " collapsed (my best link %.2f) — calling"
                        " election", self.rank, self.leader, lead, best)
            self._dethrone_task = asyncio.get_running_loop() \
                .create_task(self.call_election())

    async def call_election(self) -> None:
        # campaign above every epoch seen OR promised: a promise given
        # to another candidate in epoch e blocks acks at e, so my bid
        # must exceed it to collect fresh promises
        self.epoch = max(self.epoch, self._promised[0]) + 1
        if self.epoch % 2 == 0:   # odd = electing (Elector convention)
            self.epoch += 1
        self.electing = True
        self.leader = None
        self._acks = {self.rank}
        self._promised = (self.epoch, self.rank)
        if self.n == 1:
            await self._declare_victory()
            return
        log.info("mon.%d: calling election (epoch %d)", self.rank,
                 self.epoch)
        for peer in range(self.n):
            if peer != self.rank:
                await self.send(peer, MMonElection(
                    E_PROPOSE, self.epoch, self.rank,
                    score=self.my_score()))
        self._arm_timer()

    def _arm_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
        timeout = float(self.config.get("mon_election_timeout", 2.5))
        timeout *= 1.0 + random.random() * 0.3

        async def expire():
            await asyncio.sleep(timeout)
            if self.electing:
                await self.call_election()

        self._timer = asyncio.get_running_loop().create_task(expire())

    async def _declare_victory(self) -> None:
        self.epoch += 1            # even = stable
        self.electing = False
        self.leader = self.rank
        self.quorum = set(self._acks)
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        log.info("mon.%d: won election epoch %d (quorum %s)", self.rank,
                 self.epoch, sorted(self.quorum))
        for peer in range(self.n):
            if peer != self.rank:
                await self.send(peer, MMonElection(
                    E_VICTORY, self.epoch, self.rank,
                    quorum=sorted(self.quorum)))
        await self.on_win(self.epoch, self.quorum)

    async def handle(self, msg: MMonElection) -> None:
        if msg.kind == E_PING:
            await self.send(msg.rank, MMonElection(
                E_PONG, msg.epoch, self.rank))
            return
        if msg.kind == E_PONG:
            self._pong_pending.discard(msg.rank)
            self._ever_ponged.add(msg.rank)
            self.tracker.report(msg.rank, True)
            return
        if msg.kind == E_PROPOSE:
            if self._should_defer(msg):
                # one promise per epoch: ack only a bid NEWER than the
                # last promise (re-ack the same candidate is fine)
                pe, pr = self._promised
                if msg.epoch < pe or (msg.epoch == pe
                                      and msg.rank != pr):
                    return  # promised elsewhere; its timeout rebids
                self._promised = (msg.epoch, msg.rank)
                self.epoch = max(self.epoch, msg.epoch)
                self.electing = True
                self.leader = None
                self._arm_timer()   # re-elect if it never wins
                await self.send(msg.rank, MMonElection(
                    E_ACK, msg.epoch, self.rank))
            else:
                # I am the better candidate (lower rank under CLASSIC;
                # better-connected under CONNECTIVITY): push my own
                # candidacy — the strategy's convergence rule
                await self.call_election()
        elif msg.kind == E_ACK:
            if self.electing and msg.epoch == self.epoch:
                self._acks.add(msg.rank)
                if len(self._acks) >= self.majority:
                    await self._declare_victory()
            elif not self.electing and self.leader == self.rank and \
                    msg.epoch == self.epoch - 1 and \
                    msg.rank not in self.quorum:
                # late ack from a slow peer: absorb it into the quorum
                # (it gets commits/leases either way — only the stat
                # surface and victory broadcast record membership)
                self.quorum.add(msg.rank)
        elif msg.kind == E_VICTORY:
            if msg.epoch >= self.epoch:
                self.epoch = msg.epoch
                self.electing = False
                self.leader = msg.rank
                self.quorum = set(msg.quorum or [])
                if self._timer is not None:
                    self._timer.cancel()
                    self._timer = None
                await self.on_lose(msg.epoch, msg.rank)
                if msg.rank > self.rank and self._should_preempt(msg):
                    # a worse candidate leads while I am alive: take
                    # the quorum back (Ceph: a booting lower rank calls
                    # an election and wins it) — under CONNECTIVITY
                    # only when I am demonstrably better connected,
                    # else a lossy low-rank mon thrashes the quorum
                    await self.call_election()

    def _should_preempt(self, msg: MMonElection) -> bool:
        if self.strategy != STRATEGY_CONNECTIVITY:
            return True
        return self.my_score() > \
            self.tracker.score(msg.rank) + self._margin()

    def shutdown(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._ping_task is not None:
            self._ping_task.cancel()
            self._ping_task = None
        if self._dethrone_task is not None:
            self._dethrone_task.cancel()
            self._dethrone_task = None


class Paxos:
    """One Paxos value stream (the OSDMap incremental log)."""

    def __init__(self, rank: int, n: int,
                 send: Callable[[int, Any], Awaitable[None]],
                 store,
                 apply_fn: Callable[[int, bytes, Any], None],
                 snapshot_fn: Callable[[], bytes],
                 install_fn: Callable[[int, bytes, Any], None],
                 config: Dict[str, Any]):
        """apply_fn(version, value, txn): apply one committed value and
        stage any derived durable state into txn.
        snapshot_fn() -> full-state blob for OP_FULL catch-up.
        install_fn(version, blob, txn): adopt a full-state snapshot."""
        self.rank = rank
        self.n = n
        self.send = send
        self.store = store if store is not None else MemStore()
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.install_fn = install_fn
        self.config = dict(DEFAULTS)
        self.config.update(config or {})
        # durable state
        self.last_pn = 0          # highest PN promised (collect)
        self.accepted_pn = 0      # PN of the collect we accepted
        # PN of MY current reign (leader only).  accepted_pn can be
        # overwritten by a rival's higher-PN collect while we still
        # think we lead; proposing with accepted_pn would then make two
        # proposers share one PN and peons would accept both BEGINs for
        # the same version — divergent commits.  _begin proposes with
        # _my_pn and steps down on mismatch (quorum-safety guard).
        self._my_pn = 0
        self.last_committed = 0
        self.first_committed = 0
        self.uncommitted: Optional[tuple] = None  # (pn, v, value)
        self._load()
        # volatile
        self.leading = False
        self.quorum: Set[int] = set()
        self.active = False       # leader: collect phase done
        self.lease_expiry = 0.0   # peon: monotonic deadline
        self._last: Dict[int, MMonPaxos] = {}
        self._accepts: Set[int] = set()
        self._begin_version = 0
        self._accept_event: Optional[asyncio.Event] = None
        self._propose_lock = lockdep.Lock("paxos.propose")
        self._lease_task: Optional[asyncio.Task] = None
        self.on_leader_dead: Optional[Callable[[], Awaitable[None]]] = \
            None

    # -- durability --------------------------------------------------------

    def _load(self) -> None:
        g = self.store.get
        self.last_pn = int((g("paxos", b"last_pn") or b"0").decode())
        self.accepted_pn = int(
            (g("paxos", b"accepted_pn") or b"0").decode())
        self.last_committed = int(
            (g("paxos", b"last_committed") or b"0").decode())
        self.first_committed = int(
            (g("paxos", b"first_committed") or b"0").decode())
        unc = g("paxos", b"uncommitted")
        if unc:
            pn, v, value = unc.split(b":", 2)
            self.uncommitted = (int(pn), int(v), value)

    def _stage(self, t) -> None:
        t.set("paxos", b"last_pn", str(self.last_pn).encode())
        t.set("paxos", b"accepted_pn", str(self.accepted_pn).encode())
        t.set("paxos", b"last_committed",
              str(self.last_committed).encode())
        t.set("paxos", b"first_committed",
              str(self.first_committed).encode())
        if self.uncommitted is not None:
            pn, v, value = self.uncommitted
            t.set("paxos", b"uncommitted",
                  b"%d:%d:" % (pn, v) + value)
        else:
            t.set("paxos", b"uncommitted", b"")

    def _persist(self, mutate=None) -> None:
        t = self.store.get_transaction()
        self._stage(t)
        if mutate is not None:
            mutate(t)
        self.store.submit_transaction_sync(t)

    def log_value(self, v: int) -> Optional[bytes]:
        return self.store.get("paxos_log", v.to_bytes(8, "big"))

    def _stage_log(self, t, v: int, value: bytes) -> None:
        t.set("paxos_log", v.to_bytes(8, "big"), value)
        max_log = int(self.config["paxos_max_log"])
        floor = max(0, v - max_log)
        if floor > self.first_committed:
            t.rm_range_keys("paxos_log", (0).to_bytes(8, "big"),
                            floor.to_bytes(8, "big"))
            self.first_committed = floor

    # -- helpers -----------------------------------------------------------

    @property
    def majority(self) -> int:
        return self.n // 2 + 1

    def _new_pn(self) -> int:
        pn = (max(self.last_pn, self.accepted_pn) // 100 + 1) * 100 \
            + self.rank
        self.last_pn = pn
        return pn

    def lease_valid(self) -> bool:
        if self.leading:
            return self.active
        return time.monotonic() < self.lease_expiry

    # -- leader ------------------------------------------------------------

    async def leader_init(self, quorum: Set[int]) -> None:
        """Collect phase (Paxos::collect): learn peers' state, recover
        any uncommitted value, bring stragglers up to date."""
        self.leading = True
        self.active = False
        self.quorum = set(quorum)
        self._stop_lease()
        if self.n == 1:
            if self.uncommitted is not None:
                # a value accepted but not committed before a crash:
                # with no peers its fate is ours alone — commit it
                pn, v, value = self.uncommitted
                if v == self.last_committed + 1:
                    self._commit_value(v, value)
            self._my_pn = self.accepted_pn
            self.active = True
            return
        pn = self._new_pn()
        self.accepted_pn = pn
        self._my_pn = pn
        self._persist()
        self._last = {}
        collect = MMonPaxos(OP_COLLECT, pn=pn,
                            last_committed=self.last_committed,
                            first_committed=self.first_committed)
        # all peers, not just the election quorum: a mon whose ack
        # arrived late still syncs and receives leases — only the
        # MAJORITY gate below decides progress
        for peer in range(self.n):
            if peer != self.rank:
                await self.send(peer, collect)
        # wait for a majority of LASTs (self counts)
        deadline = time.monotonic() + float(
            self.config["mon_accept_timeout"])
        while len(self._last) + 1 < self.majority:
            if time.monotonic() > deadline:
                log.warning("mon.%d: collect timed out (%d/%d)",
                            self.rank, len(self._last) + 1,
                            self.majority)
                if self.on_leader_dead is not None:
                    await self.on_leader_dead()
                return
            await asyncio.sleep(0.02)
        # sync FORWARD first: a lagging (or freshly revived, storeless)
        # mon that wins on rank priority must adopt the quorum's
        # committed history before proposing anything — otherwise it
        # would fork acknowledged commits.  Pull from the most advanced
        # peer and wait until caught up.
        max_lc = max([last.last_committed
                      for last in self._last.values()]
                     + [self.last_committed])
        if max_lc > self.last_committed:
            ahead = max(self._last,
                        key=lambda p: self._last[p].last_committed)
            log.info("mon.%d: behind quorum (lc %d < %d), pulling from"
                     " mon.%d", self.rank, self.last_committed, max_lc,
                     ahead)
            await self.send(ahead, MMonPaxos(
                OP_PULL, last_committed=self.last_committed))
            while self.last_committed < max_lc:
                if time.monotonic() > deadline:
                    log.warning("mon.%d: catch-up timed out (lc %d <"
                                " %d)", self.rank, self.last_committed,
                                max_lc)
                    if self.on_leader_dead is not None:
                        await self.on_leader_dead()
                    return
                await asyncio.sleep(0.02)
        # adopt the newest uncommitted value seen (highest accepted_pn)
        best = self.uncommitted
        for last in self._last.values():
            if last.version and last.value:
                cand = (last.pn, last.version, last.value)
                if cand[1] == self.last_committed + 1 and \
                        (best is None or cand[0] > best[0]):
                    best = cand
        # bring lagging peers up to date
        for peer, last in self._last.items():
            if last.last_committed < self.last_committed:
                await self._share(peer, last.last_committed)
        self.active = True
        self._start_lease()
        if best is not None and best[1] == self.last_committed + 1:
            log.info("mon.%d: re-proposing uncommitted v%d from pn %d",
                     self.rank, best[1], best[0])
            await self._begin(best[2])

    async def _share(self, peer: int, peer_lc: int) -> None:
        """Ship committed values (or a snapshot past the trim floor)."""
        if peer_lc < self.first_committed:
            await self.send(peer, MMonPaxos(
                OP_FULL, last_committed=self.last_committed,
                value=self.snapshot_fn()))
            return
        values = {}
        for v in range(peer_lc + 1, self.last_committed + 1):
            val = self.log_value(v)
            if val is None:
                await self.send(peer, MMonPaxos(
                    OP_FULL, last_committed=self.last_committed,
                    value=self.snapshot_fn()))
                return
            values[v] = val
        await self.send(peer, MMonPaxos(
            OP_COMMIT, pn=self.accepted_pn,
            last_committed=self.last_committed, values=values))

    async def propose(self, value: bytes) -> bool:
        """Leader-only: replicate one value; True once committed on a
        majority.  Serialized — one in-flight proposal (Paxos.cc's
        single-pipeline discipline)."""
        async with self._propose_lock:
            if not (self.leading and self.active):
                return False
            return await self._begin(value)

    async def _begin(self, value: bytes) -> bool:
        v = self.last_committed + 1
        pn = self._my_pn
        if pn != self.accepted_pn or not self.leading:
            # a rival's higher-PN collect superseded this reign between
            # proposals (see _my_pn) — step down instead of proposing
            # under a PN that is no longer exclusively ours
            log.warning("mon.%d: reign pn %d superseded by %d —"
                        " stepping down", self.rank, pn,
                        self.accepted_pn)
            self.leading = False
            self.active = False
            self._stop_lease()
            if self.on_leader_dead is not None:
                await self.on_leader_dead()
            return False
        self.uncommitted = (pn, v, value)
        self._persist()
        self._accepts = {self.rank}
        self._begin_version = v
        self._accept_event = asyncio.Event()
        if self.n > 1:
            msg = MMonPaxos(OP_BEGIN, pn=pn, version=v, value=value,
                            last_committed=self.last_committed)
            for peer in range(self.n):
                if peer != self.rank:
                    await self.send(peer, msg)
            try:
                await asyncio.wait_for(
                    self._accept_event.wait(),
                    float(self.config["mon_accept_timeout"]))
            except asyncio.TimeoutError:
                log.warning("mon.%d: begin v%d pn %d: no majority"
                            " (%d/%d) — stepping down", self.rank, v,
                            pn, len(self._accepts), self.majority)
                self.active = False
                if self.on_leader_dead is not None:
                    await self.on_leader_dead()
                return False
        self._commit_value(v, value)
        if self.n > 1:
            commit = MMonPaxos(OP_COMMIT, pn=pn,
                               last_committed=self.last_committed,
                               values={v: value})
            for peer in range(self.n):
                if peer != self.rank:
                    await self.send(peer, commit)
        return True

    def _commit_value(self, v: int, value: bytes) -> None:
        """Durable commit + apply in ONE store transaction."""
        assert v == self.last_committed + 1
        self.last_committed = v
        self.uncommitted = None

        def mutate(t):
            self._stage_log(t, v, value)
            self.apply_fn(v, value, t)

        self._persist(mutate)

    # -- lease -------------------------------------------------------------

    def _start_lease(self) -> None:
        self._stop_lease()
        if self.n == 1:
            return

        async def lease_loop():
            lease = float(self.config["mon_lease"])
            interval = lease * float(
                self.config["mon_lease_renew_interval_factor"])
            while self.leading and self.active:
                msg = MMonPaxos(OP_LEASE,
                                last_committed=self.last_committed,
                                lease=lease)
                for peer in range(self.n):
                    if peer != self.rank:
                        try:
                            await self.send(peer, msg)
                        except Exception:
                            pass
                await asyncio.sleep(interval)

        self._lease_task = asyncio.get_running_loop().create_task(
            lease_loop())

    def _stop_lease(self) -> None:
        if self._lease_task is not None:
            self._lease_task.cancel()
            self._lease_task = None

    # -- peon / message handling -------------------------------------------

    def become_peon(self) -> None:
        self.leading = False
        self.active = False
        self._stop_lease()
        self.lease_expiry = time.monotonic() + float(
            self.config["mon_lease"])

    async def handle(self, from_rank: int, msg: MMonPaxos) -> None:
        op = msg.op
        if op == OP_COLLECT:
            if msg.pn > max(self.last_pn, self.accepted_pn):
                if self.leading:
                    # a rival reign with a higher PN exists: demote NOW
                    # (election-resets-paxos discipline,
                    # /root/reference/src/mon/Paxos.cc handle_collect
                    # via election) — a stale leader must never keep
                    # proposing under the rival's PN
                    log.warning("mon.%d: higher-pn collect %d from"
                                " mon.%d while leading — demoting",
                                self.rank, msg.pn, from_rank)
                    self.leading = False
                    self.active = False
                    self._stop_lease()
                self.last_pn = msg.pn
                self.accepted_pn = msg.pn
                reply = MMonPaxos(
                    OP_LAST, pn=msg.pn,
                    last_committed=self.last_committed,
                    first_committed=self.first_committed)
                if self.uncommitted is not None:
                    upn, uv, uval = self.uncommitted
                    reply.pn = msg.pn
                    reply.version = uv
                    reply.value = uval
                    # carry the accepting PN so the leader can pick the
                    # newest among competing uncommitted values
                    reply.uncommitted_pn = upn
                self._persist()
                await self.send(from_rank, reply)
            # a stale collect is ignored (its proposer will retry with
            # a higher PN after the next election)
        elif op == OP_LAST:
            if self.leading and msg.pn == self.accepted_pn:
                m = msg
                if m.version and m.uncommitted_pn:
                    m.pn = m.uncommitted_pn
                self._last[from_rank] = m
        elif op == OP_BEGIN:
            if msg.pn >= self.accepted_pn:
                if self.leading and from_rank != self.rank:
                    self.leading = False
                    self.active = False
                    self._stop_lease()
                self.accepted_pn = msg.pn
                self.uncommitted = (msg.pn, msg.version, msg.value)
                self._persist()
                self.lease_expiry = time.monotonic() + float(
                    self.config["mon_lease"])
                await self.send(from_rank, MMonPaxos(
                    OP_ACCEPT, pn=msg.pn, version=msg.version))
        elif op == OP_ACCEPT:
            # version must match the CURRENT proposal: the pn is
            # constant across a reign, so a stale in-flight accept for
            # the previous value would otherwise count toward this
            # one's majority (commit without a true majority)
            if self.leading and msg.pn == self.accepted_pn and \
                    msg.version == getattr(self, "_begin_version", -1):
                self._accepts.add(from_rank)
                if len(self._accepts) >= self.majority and \
                        self._accept_event is not None:
                    self._accept_event.set()
        elif op == OP_COMMIT:
            await self._handle_commit(from_rank, msg)
        elif op == OP_LEASE:
            self.lease_expiry = time.monotonic() + (msg.lease or float(
                self.config["mon_lease"]))
            if msg.last_committed > self.last_committed:
                await self.send(from_rank, MMonPaxos(
                    OP_PULL, last_committed=self.last_committed))
        elif op == OP_PULL:
            # answered by ANYONE holding newer committed history (a
            # catching-up leader pulls from a peon; a gapped peon pulls
            # from the leader) — committed values are immutable, so
            # sharing them is always safe
            if msg.last_committed < self.last_committed:
                await self._share(from_rank, msg.last_committed)
        elif op == OP_FULL:
            if msg.last_committed > self.last_committed:
                v = msg.last_committed
                self.last_committed = v
                self.first_committed = v
                self.uncommitted = None

                def mutate(t):
                    self.install_fn(v, msg.value, t)

                self._persist(mutate)

    async def _handle_commit(self, from_rank: int,
                             msg: MMonPaxos) -> None:
        applied = False
        for v in sorted(msg.values or {}):
            if v == self.last_committed + 1:
                self._commit_value(v, msg.values[v])
                applied = True
        if msg.last_committed > self.last_committed:
            # gap: ask the leader for the missing range
            await self.send(from_rank, MMonPaxos(
                OP_PULL, last_committed=self.last_committed))
        if applied:
            self.lease_expiry = time.monotonic() + float(
                self.config["mon_lease"])

    def shutdown(self) -> None:
        self._stop_lease()

"""Run a mini-mon as a real process: python -m ceph_tpu.mon

Prints `MON_ADDR <host:port>` on stdout once bound (the ceph-helpers
run_mon contract: callers parse the address to wire up OSDs/clients).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.mon import MonDaemon


async def _main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-osds", type=int, required=True)
    ap.add_argument("--osds-per-host", type=int, default=2)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--config", type=str, default="{}",
                    help="JSON mon config overrides")
    ap.add_argument("--store-path", type=str, default="",
                    help="durable MonitorDBStore (SQLite); a restart"
                         " on the same path reloads cluster state")
    args = ap.parse_args()
    store = None
    if args.store_path:
        from ceph_tpu.kv import SQLiteDB

        store = SQLiteDB(args.store_path)
        store.create_and_open()
    mon = MonDaemon(args.num_osds, osds_per_host=args.osds_per_host,
                    config=json.loads(args.config), store=store)
    addr = await mon.start(port=args.port)
    print(f"MON_ADDR {addr}", flush=True)
    try:
        await asyncio.Event().wait()  # serve until killed
    finally:
        await mon.shutdown()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

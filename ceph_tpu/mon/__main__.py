"""Run a mini-mon as a real process: python -m ceph_tpu.mon

Prints `MON_ADDR <host:port>` on stdout once bound (the ceph-helpers
run_mon contract: callers parse the address to wire up OSDs/clients).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.mon import MonDaemon


async def _main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-osds", type=int, required=True)
    ap.add_argument("--osds-per-host", type=int, default=2)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--rank", type=int, default=0,
                    help="this mon's rank in the monmap")
    ap.add_argument("--mon-addrs", type=str, default="",
                    help="comma-separated monmap (host:port by rank);"
                         " enables multi-mon quorum.  This mon binds"
                         " its own rank's port from the list.")
    ap.add_argument("--config", type=str, default="{}",
                    help="JSON mon config overrides")
    ap.add_argument("--store-path", type=str, default="",
                    help="durable MonitorDBStore (SQLite); a restart"
                         " on the same path reloads cluster state")
    args = ap.parse_args()
    store = None
    if args.store_path:
        from ceph_tpu.kv import SQLiteDB

        store = SQLiteDB(args.store_path)
        store.create_and_open()
    mon_addrs = [a for a in args.mon_addrs.split(",") if a]
    host, port = "127.0.0.1", args.port
    if mon_addrs:
        host, port_s = mon_addrs[args.rank].rsplit(":", 1)
        port = int(port_s)
    mon = MonDaemon(args.num_osds, osds_per_host=args.osds_per_host,
                    config=json.loads(args.config), store=store,
                    rank=args.rank, mon_addrs=mon_addrs)
    addr = await mon.start(host=host, port=port)
    print(f"MON_ADDR {addr}", flush=True)
    try:
        await asyncio.Event().wait()  # serve until killed
    finally:
        await mon.shutdown()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

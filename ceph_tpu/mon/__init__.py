"""Mini-monitor: the cluster control plane (single- or multi-instance).

Reference parity: Monitor + OSDMonitor
(/root/reference/src/mon/Monitor.cc, OSDMonitor.cc).  With one mon the
PaxosService commit discipline survives as: every map mutation is an
epoch bump whose incremental is pushed to all subscribers.  With a
multi-mon monmap, every mutation is a Paxos proposal (mon/paxos.py:
collect/begin/accept/commit/lease + rank-priority elections); only the
leader mutates, peons forward boot/failure/commands to it (MForward
role) and serve map reads from their committed state; a 2-of-3 quorum
survives the loss of any one mon, including the leader mid-write.

Covered OSDMonitor behaviors:
- OSD lifecycle: MOSDBoot marks up + records the address
  (OSDMonitor::prepare_boot); liveness beacons double as boot.
- Failure adjudication (prepare_failure OSDMonitor.cc:2739,
  check_failure :3156-3185): an OSD is marked down when enough distinct
  reporters (mon_osd_min_down_reporters) have current failure reports
  and the oldest report has aged past an ADAPTIVE grace: base
  osd_heartbeat_grace plus a laggy term from the target's own history
  (halflife-decayed laggy_probability/laggy_interval, the :3180-3185
  math) — flapping OSDs earn longer grace.
- Pool/profile commands (OSDMonitor.cc:7373-7712): erasure-code-profile
  set (validated by instantiating the codec), pool create
  replicated/erasure (EC pools get a rule from the codec like
  create_rule), osd down/out/in, status/health.
- Health checks (mon/health_check.h role): OSD_DOWN / PG_DEGRADED
  summary served by `status` and `health` commands.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ceph_tpu.common import lockdep
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.mon import paxos as paxos_mod
from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    MAuth,
    MAuthReply,
    MConfig,
    MLog,
    Message,
    MGetMap,
    MMonCommand,
    MMonCommandReply,
    MMonElection,
    MMonForward,
    MMonForwardReply,
    MMonPaxos,
    MOSDBoot,
    MOSDFailure,
    MOSDMapMsg,
    decode_message,
)
from ceph_tpu.osd.osdmap import (
    CEPH_OSD_DESTROYED,
    CEPH_OSD_IN,
    CEPH_OSD_UP,
    Incremental,
    OSDMap,
    TYPE_ERASURE,
    TYPE_REPLICATED,
)

log = logging.getLogger("mon")

DEFAULTS = {
    "mon_osd_min_down_reporters": 2,
    "osd_heartbeat_grace": 20.0,
    "mon_osd_laggy_halflife": 3600.0,
    "mon_osd_laggy_weight": 0.3,
    "mon_osd_adjust_heartbeat_grace": True,
}


class FailureReport:
    __slots__ = ("first_reported", "last_reported", "failed_for")

    def __init__(self, now: float, failed_for: float):
        self.first_reported = now
        self.last_reported = now
        self.failed_for = failed_for


class MonDaemon:
    """One monitor instance (rank r of a monmap of n; n=1 keeps the
    single-authoritative shape with zero consensus traffic)."""

    def __init__(self, num_osds: int, osds_per_host: int = 2,
                 config: Optional[Dict[str, Any]] = None,
                 store=None, rank: int = 0,
                 mon_addrs: Optional[List[str]] = None):
        self.config = dict(DEFAULTS)
        self.config.update(config or {})
        from ceph_tpu.common.auth import parse_secret

        self.rank = rank
        self.mon_addrs: List[str] = list(mon_addrs or [])
        self.msgr = Messenger(
            f"mon.{rank}", secret=parse_secret(
                self.config.get("auth_secret")))
        self.msgr.secure = bool(self.config.get("auth_secure"))
        self.msgr.local_fastpath = bool(
            self.config.get("ms_local_fastpath", True))
        self.msgr.dispatcher = self._dispatch
        self.msgr.inject_socket_failures = int(
            self.config.get("ms_inject_socket_failures", 0) or 0)
        self.msgr.inject_internal_delays = float(
            self.config.get("ms_inject_internal_delays", 0) or 0)
        self.msgr.apply_compress_config(self.config)
        # durable state (the MonitorDBStore role,
        # /root/reference/src/mon/MonitorDBStore.h): every commit writes
        # the incremental, the resulting full map, and the auxiliary
        # adjudication state into the KeyValueDB in one transaction, so
        # a mon restart is a reload, not cluster amnesia
        self.store = store
        self._subscribers: List[Connection] = []
        self._inc_log: Dict[int, bytes] = {}
        self._inc_log_max = 1000
        # failure bookkeeping (OSDMonitor::failure_info_t)
        self._failure_reports: Dict[int, Dict[int, FailureReport]] = {}
        # laggy history for adaptive grace (osd_xinfo_t)
        self._laggy_probability: Dict[int, float] = {}
        self._laggy_interval: Dict[int, float] = {}
        self._down_at: Dict[int, float] = {}
        self._up_from: Dict[int, int] = {}  # boot epoch per osd
        self._check_task: Optional[asyncio.Task] = None
        self._lease_watch_task: Optional[asyncio.Task] = None
        # one map mutation in flight at a time (the PaxosService
        # single-proposal round): handlers read the map, build an
        # incremental, and propose under this lock
        self._mutation_lock = lockdep.Lock("mon.mutation")
        # centralized config (ConfigMonitor role): {section: {k: v}},
        # quorum-replicated through paxos, pushed to subscribers
        self._config_kv: Dict[str, Dict[str, str]] = {}
        self._config_version = 0
        # cluster log ring (LogMonitor role): one place to read a
        # multi-daemon incident instead of grepping N process logs
        from collections import deque

        self._cluster_log: "deque" = deque(maxlen=2048)
        # crash reports (the mgr crash module role, kept on the mon so
        # reports are quorum-replicated and survive any single daemon):
        # crash_id -> report dict (+ "archived" flag)
        self._crash: Dict[str, Dict[str, Any]] = {}
        # forwarded-command reply routing (MForward role)
        self._fwd_tid = 0
        self._fwd_pending: Dict[int, Tuple[Connection, int]] = {}
        self.paxos: Optional[paxos_mod.Paxos] = None
        self.elector: Optional[paxos_mod.Elector] = None
        if store is not None and self._load_store():
            return
        self.osdmap = OSDMap.build_simple(num_osds,
                                          osds_per_host=osds_per_host)
        # all OSDs start down (exist + in); boot marks them up
        for osd in range(num_osds):
            self.osdmap.osd_state[osd] &= ~CEPH_OSD_UP
        # from here the map mutates only via apply_incremental
        self.osdmap.enable_placement_cache()
        if store is not None:
            self._persist(None)

    def _load_store(self) -> bool:
        raw = self.store.get("mon", b"osdmap_full")
        if raw is None:
            return False
        self.osdmap = OSDMap.decode(raw)
        self.osdmap.enable_placement_cache()
        # load at most the newest _inc_log_max incrementals (the store
        # is trimmed on commit, but never trust unbounded history)
        loaded = [(int.from_bytes(key, "big"), val)
                  for key, val in self.store.get_iterator("osdmap")]
        for epoch, val in loaded[-self._inc_log_max:]:
            self._inc_log[epoch] = val
        cfg = self.store.get("mon", b"config")
        if cfg:
            doc = json.loads(cfg.decode())
            self._config_kv = doc.get("kv", {})
            self._config_version = int(doc.get("version", 0))
        crash = self.store.get("mon", b"crash")
        if crash:
            self._crash = json.loads(crash.decode())
        aux = self.store.get("mon", b"aux")
        if aux:
            doc = json.loads(aux.decode())
            self._laggy_probability = {
                int(k): v for k, v in doc["laggy_probability"].items()}
            self._laggy_interval = {
                int(k): v for k, v in doc["laggy_interval"].items()}
            self._up_from = {int(k): v
                             for k, v in doc["up_from"].items()}
        log.info("mon: reloaded epoch %d from store", self.osdmap.epoch)
        return True

    def _stage_mon(self, t, inc_raw: Optional[bytes]) -> None:
        """Stage the mon's map state into a store transaction."""
        if inc_raw is not None:
            t.set("osdmap",
                  self.osdmap.epoch.to_bytes(8, "big"), inc_raw)
            # keep the durable inc log bounded like the in-memory one
            floor = max(0, self.osdmap.epoch - self._inc_log_max)
            t.rm_range_keys("osdmap", (0).to_bytes(8, "big"),
                            floor.to_bytes(8, "big"))
        t.set("mon", b"osdmap_full", self.osdmap.encode())
        t.set("mon", b"config", json.dumps({
            "kv": self._config_kv,
            "version": self._config_version,
        }).encode())
        t.set("mon", b"crash", json.dumps(self._crash).encode())
        t.set("mon", b"aux", json.dumps({
            "laggy_probability": self._laggy_probability,
            "laggy_interval": self._laggy_interval,
            "up_from": self._up_from,
        }).encode())

    def _persist(self, inc_raw: Optional[bytes]) -> None:
        """One durable transaction per commit (Paxos commit point)."""
        t = self.store.get_transaction()
        self._stage_mon(t, inc_raw)
        self.store.submit_transaction_sync(t)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        # native prewarm rides msgr.bind (Messenger._prewarm_native)
        addr = await self.msgr.bind(host, port)
        self._check_task = asyncio.get_running_loop().create_task(
            self._check_failures_loop())
        if not self.mon_addrs and self.rank == 0:
            # standalone mon: a 1-entry monmap — paxos runs the same
            # commit pipeline with zero consensus traffic
            self.mon_addrs = [addr]
        if self.mon_addrs:
            await self.start_consensus()
        return addr

    async def set_peers(self, mon_addrs: List[str]) -> None:
        """Install the monmap (addresses by rank) once every mon is
        bound, then start elections; for dynamically-bound test
        clusters this replaces passing mon_addrs to the constructor."""
        self.mon_addrs = list(mon_addrs)
        await self.start_consensus()

    async def start_consensus(self) -> None:
        n = len(self.mon_addrs)
        self.paxos = paxos_mod.Paxos(
            self.rank, n, self._send_rank, self.store,
            self._paxos_apply, self._paxos_snapshot,
            self._paxos_install, self.config)
        self.paxos.on_leader_dead = self._on_quorum_lost
        self.elector = paxos_mod.Elector(
            self.rank, n, self._send_rank, self._on_win,
            self._on_lose, self.config)
        if self.store is not None:
            raw = self.store.get("mon", b"election_epoch")
            if raw:
                self.elector.epoch = int(raw)
        await self.elector.start()
        if n > 1:
            self._lease_watch_task = \
                asyncio.get_running_loop().create_task(
                    self._lease_watch())

    async def shutdown(self) -> None:
        if self._check_task is not None:
            self._check_task.cancel()
        if self._lease_watch_task is not None:
            self._lease_watch_task.cancel()
        if self.elector is not None:
            self.elector.shutdown()
        if self.paxos is not None:
            self.paxos.shutdown()
        await self.msgr.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.addr

    def is_leader(self) -> bool:
        return self.elector is None or self.elector.leader == self.rank

    # -- consensus plumbing ------------------------------------------------

    async def _send_rank(self, peer: int, msg: Message) -> None:
        if hasattr(msg, "from_rank"):
            msg.from_rank = self.rank
        try:
            await self.msgr.send_to(self.mon_addrs[peer], msg)
        except (ConnectionError, OSError):
            pass  # elections/leases tolerate drops; paxos retries

    def _save_election_epoch(self) -> None:
        if self.store is not None and self.elector is not None:
            t = self.store.get_transaction()
            t.set("mon", b"election_epoch",
                  str(self.elector.epoch).encode())
            self.store.submit_transaction_sync(t)

    async def _on_win(self, epoch: int, quorum) -> None:
        self._save_election_epoch()
        self._failure_reports.clear()  # re-reported by live OSDs
        await self.paxos.leader_init(set(quorum))

    async def _on_lose(self, epoch: int, leader: int) -> None:
        self._save_election_epoch()
        self.paxos.become_peon()

    async def _on_quorum_lost(self) -> None:
        await self.elector.call_election()

    async def _lease_watch(self) -> None:
        """Peon-side leader failure detection: an expired lease (no
        leader traffic) calls a new election (Paxos lease timeout)."""
        while True:
            await asyncio.sleep(0.3)
            if self.elector is None or self.elector.electing:
                continue
            if self.is_leader():
                continue
            if not self.paxos.lease_valid():
                log.warning("mon.%d: lease expired — leader %s silent,"
                            " calling election", self.rank,
                            self.elector.leader)
                await self.elector.call_election()

    def _paxos_apply(self, v: int, value: bytes, t) -> None:
        """Committed-value application (every mon, leader and peon).
        Values are tagged: b"M"+incremental (map mutation) or
        b"C"+json (centralized config mutation) — the PaxosService
        multiplexing role collapsed onto one tag byte; untagged values
        are legacy map incrementals."""
        if value[:1] == b"R":
            doc = json.loads(value[1:].decode())
            op = doc.get("op")
            if op == "post":
                rep = doc["report"]
                self._crash.setdefault(rep["crash_id"], rep)
            elif op == "archive":
                rep = self._crash.get(doc["crash_id"])
                if rep is not None:
                    rep["archived"] = True
            elif op == "archive_all":
                for rep in self._crash.values():
                    rep["archived"] = True
            elif op == "rm":
                self._crash.pop(doc["crash_id"], None)
            self._stage_mon(t, None)
            return
        if value[:1] == b"C":
            doc = json.loads(value[1:].decode())
            section, name = doc["section"], doc["name"]
            if doc.get("value") is None:
                self._config_kv.get(section, {}).pop(name, None)
                if not self._config_kv.get(section, True):
                    self._config_kv.pop(section, None)
            else:
                sect = self._config_kv.setdefault(section, {})
                sect[name] = str(doc["value"])
            self._config_version = v
            self._stage_mon(t, None)
            self._push_config()
            return
        if value[:1] == b"M":
            value = value[1:]
        inc = Incremental.decode(value)
        self.osdmap.apply_incremental(inc)
        self._inc_log[inc.epoch] = value
        while len(self._inc_log) > self._inc_log_max:
            del self._inc_log[min(self._inc_log)]
        self._stage_mon(t, value)
        self._publish()

    def _paxos_snapshot(self) -> bytes:
        """OP_FULL payload: EVERY replicated state — the map AND the
        centralized config (a snapshot that missed config would
        silently re-persist a stale kv on the caught-up mon)."""
        m = self.osdmap.encode()
        cfg = json.dumps({"kv": self._config_kv,
                          "version": self._config_version}).encode()
        crash = json.dumps(self._crash).encode()
        return (len(m).to_bytes(8, "big") + m
                + len(cfg).to_bytes(8, "big") + cfg
                + len(crash).to_bytes(8, "big") + crash)

    def _paxos_install(self, v: int, blob: bytes, t) -> None:
        """Full-state catch-up past a trimmed log (OP_FULL)."""
        mlen = int.from_bytes(blob[:8], "big")
        self.osdmap = OSDMap.decode(blob[8:8 + mlen])
        self.osdmap.enable_placement_cache()
        rest = blob[8 + mlen:]
        if rest:
            clen = int.from_bytes(rest[:8], "big")
            doc = json.loads(rest[8:8 + clen].decode())
            self._config_kv = doc.get("kv", {})
            self._config_version = int(doc.get("version", 0))
            self._push_config()
            rest = rest[8 + clen:]
        if rest:  # crash table (older snapshots simply lack it)
            rlen = int.from_bytes(rest[:8], "big")
            self._crash = json.loads(rest[8:8 + rlen].decode())
        self._inc_log.clear()
        self._stage_mon(t, None)
        self._publish()
        log.info("mon.%d: installed full snapshot at epoch %d",
                 self.rank, self.osdmap.epoch)

    # -- map mutation ------------------------------------------------------

    async def _commit(self, inc: Incremental) -> bool:
        """Replicate one incremental through Paxos (leader only; the
        n=1 fast path commits inline with zero network traffic).
        Caller holds _mutation_lock.  Returns False when quorum could
        not commit — the caller surfaces EAGAIN and the client retries."""
        if self.paxos is None:
            # pre-consensus (constructor persistence only)
            raw = inc.encode()
            self.osdmap.apply_incremental(inc)
            self._inc_log[inc.epoch] = raw
            if self.store is not None:
                self._persist(raw)
            self._publish()
            return True
        # re-stamp under the mutation lock: the handler built the inc
        # against the map as it read it; the epoch must be the commit
        # point's successor
        inc.epoch = self.osdmap.epoch + 1
        return await self.paxos.propose(b"M" + inc.encode())

    def _push_config(self) -> None:
        msg = MConfig(self._config_version, self._config_kv)
        for conn in list(self._subscribers):
            if not conn.closed:
                self.msgr._spawn(self._send_quiet(conn, msg))

    def clog(self, level: str, who: str, message: str) -> None:
        """Append one cluster-log entry (LogMonitor ingest)."""
        self._cluster_log.append({
            "stamp": time.time(), "level": level, "who": who,
            "message": message})

    def _publish(self) -> None:
        """Push the new epoch to subscribers as the committing
        incremental alone — every subscriber (daemon or client) applies
        epochs in order and pulls missing ranges with MGetMap on a gap,
        so re-encoding and shipping the full map per commit would be
        O(map x subscribers) of pure waste."""
        epoch = self.osdmap.epoch
        inc = self._inc_log.get(epoch)
        if inc is not None:
            msg = MOSDMapMsg(epoch, incrementals=[inc])
        else:  # no incremental for this epoch: fall back to a full map
            msg = MOSDMapMsg(epoch, full_map=self.osdmap.encode())
        for conn in list(self._subscribers):
            if conn.closed:
                self._subscribers.remove(conn)
                continue
            self.msgr._spawn(self._send_quiet(conn, msg))

    async def _send_quiet(self, conn: Connection, msg: Message) -> None:
        try:
            await conn.send(msg)
        except (ConnectionError, OSError):
            pass

    # -- dispatch ----------------------------------------------------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MOSDBoot):
            if self.is_leader():
                await self._handle_boot(msg)
            else:
                await self._forward(msg)
        elif isinstance(msg, MGetMap):
            # served from committed state on ANY mon: epochs are
            # monotonic and consumers pull ranges, so a peon answering
            # slightly behind the leader is safe by construction
            if msg.subscribe and conn not in self._subscribers:
                self._subscribers.append(conn)
                # unconditionally — an EMPTY snapshot is load-bearing:
                # a resubscriber whose overrides were removed while it
                # was away must revert them
                await self._send_quiet(conn, MConfig(
                    self._config_version, self._config_kv))
            cur = self.osdmap.epoch
            since = msg.since_epoch
            if since and all(e in self._inc_log
                             for e in range(since + 1, cur + 1)):
                await conn.send(MOSDMapMsg(
                    cur, incrementals=[self._inc_log[e]
                                       for e in range(since + 1,
                                                      cur + 1)]))
            else:
                await conn.send(MOSDMapMsg(
                    cur, full_map=self.osdmap.encode(),
                    gap_unfillable=bool(since)))
        elif isinstance(msg, MOSDFailure):
            if self.is_leader():
                await self._handle_failure(msg)
            else:
                await self._forward(msg)
        elif isinstance(msg, MMonCommand):
            if self.is_leader():
                rc, out = await self.handle_command(msg.cmd)
                await conn.send(MMonCommandReply(msg.tid, rc, out))
            else:
                await self._forward(msg, conn, msg.tid)
        elif isinstance(msg, MLog):
            if self.is_leader():
                for e in msg.entries:
                    self._cluster_log.append(dict(e))
            else:
                await self._forward(msg)
        elif isinstance(msg, MAuth):
            await self._handle_auth(conn, msg)
        elif isinstance(msg, MMonElection):
            if self.elector is not None:
                await self.elector.handle(msg)
        elif isinstance(msg, MMonPaxos):
            if self.paxos is not None and msg.from_rank >= 0:
                await self.paxos.handle(msg.from_rank, msg)
        elif isinstance(msg, MMonForward):
            await self._handle_forward(conn, msg)
        elif isinstance(msg, MMonForwardReply):
            pending = self._fwd_pending.pop(msg.fwd_tid, None)
            if pending is not None:
                client_conn, tid = pending
                await self._send_quiet(client_conn, MMonCommandReply(
                    tid, msg.rc, msg.out))

    async def _handle_auth(self, conn: Connection, msg: MAuth) -> None:
        """Mon-as-KDC ticket service (CephxServiceHandler role): stage
        1 hands out a server challenge, stage 2 validates the client's
        proof of key possession and grants a signed expiring ticket.
        Served by ANY mon — the keyring is cluster-wide state."""
        from ceph_tpu.common import auth as auth_mod

        keyring = self.msgr.secret
        if keyring is None:
            await self._send_quiet(conn, MAuthReply(msg.tid, -95))
            return
        if msg.stage == 1:
            challenge = auth_mod.new_nonce()
            conn._auth_challenge = challenge
            await self._send_quiet(conn, MAuthReply(
                msg.tid, 0, server_challenge=challenge))
            return
        challenge = getattr(conn, "_auth_challenge", b"")
        key = keyring.get(msg.kid)
        ok = (bool(challenge) and key is not None
              and auth_mod.check_proof(key, msg.entity,
                                       bytes(msg.client_challenge),
                                       challenge, bytes(msg.proof)))
        if not ok:
            log.warning("mon.%d: auth proof failure for %r", self.rank,
                        msg.entity)
            await self._send_quiet(conn, MAuthReply(msg.tid, -13))
            return
        conn._auth_challenge = b""  # single use
        ticket = auth_mod.make_ticket(keyring, msg.entity)
        await self._send_quiet(conn, MAuthReply(msg.tid, 0,
                                                ticket=ticket))

    async def _forward(self, msg: Message,
                       conn: Optional[Connection] = None,
                       tid: Optional[int] = None) -> None:
        """Relay a client message to the leader (MForward role).
        Commands get reply routing via fwd_tid; boot/failure reports
        are fire-and-forget (their effect shows up in the next map)."""
        leader = self.elector.leader if self.elector else None
        if leader is None or leader == self.rank:
            if conn is not None and tid is not None:
                await self._send_quiet(conn, MMonCommandReply(
                    tid, -11, {"error": "no quorum leader (election"
                                        " in progress); retry"}))
            return
        fwd_tid = 0
        if conn is not None and tid is not None:
            self._fwd_tid += 1
            fwd_tid = self._fwd_tid
            self._fwd_pending[fwd_tid] = (conn, tid)
            while len(self._fwd_pending) > 1024:
                self._fwd_pending.pop(next(iter(self._fwd_pending)))
        try:
            await self.msgr.send_to(
                self.mon_addrs[leader],
                MMonForward(fwd_tid, msg.TAG, msg.encode()))
        except (ConnectionError, OSError):
            self._fwd_pending.pop(fwd_tid, None)

    async def _handle_forward(self, conn: Connection,
                              msg: MMonForward) -> None:
        """Leader side of the relay."""
        try:
            inner = decode_message(msg.inner_tag, msg.inner_payload)
        except Exception:
            log.exception("mon.%d: bad forwarded message", self.rank)
            return
        if not self.is_leader():
            return  # leadership moved mid-flight; sender will refresh
        if isinstance(inner, MMonCommand):
            rc, out = await self.handle_command(inner.cmd)
            if msg.fwd_tid:
                await self._send_quiet(conn, MMonForwardReply(
                    msg.fwd_tid, rc, out))
        elif isinstance(inner, MOSDBoot):
            await self._handle_boot(inner)
        elif isinstance(inner, MOSDFailure):
            await self._handle_failure(inner)
        elif isinstance(inner, MLog):
            for e in inner.entries:
                self._cluster_log.append(dict(e))

    # -- boot / failure ----------------------------------------------------

    async def _handle_boot(self, msg: MOSDBoot) -> None:
        osd = msg.osd
        if not (0 <= osd < self.osdmap.max_osd):
            return
        now = time.monotonic()
        # returning after a mon-ordered down: update laggy history
        # (OSDMonitor laggy tracking feeding the adaptive grace)
        down_at = self._down_at.pop(osd, None)
        if down_at is not None:
            halflife = self.config["mon_osd_laggy_halflife"]
            weight = self.config["mon_osd_laggy_weight"]
            interval = now - down_at
            decay = 0.5 ** (interval / halflife)
            self._laggy_probability[osd] = min(
                1.0, self._laggy_probability.get(osd, 0.0) * decay
                + weight)
            self._laggy_interval[osd] = (
                self._laggy_interval.get(osd, 0.0) * decay
                + interval * weight)
        self._failure_reports.pop(osd, None)
        async with self._mutation_lock:
            if self.osdmap.is_up(osd) and \
                    self.osdmap.osd_addrs.get(osd) == msg.addr:
                return
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_up_osds[osd] = msg.addr
            if not self.osdmap.is_in(osd):
                inc.new_weight[osd] = CEPH_OSD_IN
            if self.osdmap.is_destroyed(osd):
                # a lost OSD that comes back rejoins with normal probe
                # semantics (its declared-gone window is over)
                inc.new_state[osd] = CEPH_OSD_DESTROYED  # XOR: clear
            if not await self._commit(inc):
                return  # no quorum; the OSD's boot loop retries
        self._up_from[osd] = self.osdmap.epoch
        log.info("mon.%d: osd.%d booted at %s (epoch %d)", self.rank,
                 osd, msg.addr, self.osdmap.epoch)

    async def _handle_failure(self, msg: MOSDFailure) -> None:
        target = msg.target_osd
        if not self.osdmap.is_up(target):
            return
        # a report from before the target's current boot is about a
        # previous incarnation (OSDMonitor::prepare_failure epoch check)
        if msg.epoch < self._up_from.get(target, 0):
            return
        reports = self._failure_reports.setdefault(target, {})
        now = time.monotonic()
        report = reports.get(msg.reporter)
        if report is None:
            reports[msg.reporter] = FailureReport(now, msg.failed_for)
        else:
            report.last_reported = now
            report.failed_for = msg.failed_for
        await self._check_failure(target, now)

    def _grace(self, target: int) -> float:
        """Adaptive grace (OSDMonitor.cc:3180-3185): base + decayed
        laggy_probability * laggy_interval."""
        grace = float(self.config["osd_heartbeat_grace"])
        if self.config["mon_osd_adjust_heartbeat_grace"]:
            prob = self._laggy_probability.get(target, 0.0)
            interval = self._laggy_interval.get(target, 0.0)
            if prob > 0.05 and interval > 0:
                grace += prob * interval
        return grace

    async def _check_failure(self, target: int, now: float) -> None:
        reports = self._failure_reports.get(target, {})
        if len(reports) < int(self.config["mon_osd_min_down_reporters"]):
            return
        oldest = min(r.first_reported for r in reports.values())
        max_failed = max(r.failed_for for r in reports.values())
        if max(now - oldest, max_failed) < self._grace(target):
            return
        log.info("mon.%d: marking osd.%d down (%d reporters, grace"
                 " %.1fs)", self.rank, target, len(reports),
                 self._grace(target))
        self.clog("WRN", f"mon.{self.rank}",
                  f"osd.{target} marked down ({len(reports)}"
                  " reporters)")
        self._failure_reports.pop(target, None)
        self._down_at[target] = now
        async with self._mutation_lock:
            if not self.osdmap.is_up(target):
                return
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_state[target] = CEPH_OSD_UP  # XOR: up -> down
            await self._commit(inc)

    async def _check_failures_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            if not self.is_leader():
                # failure adjudication is the leader's job; a peon's
                # stale report set resets on the next election win
                continue
            now = time.monotonic()
            for target in list(self._failure_reports):
                await self._check_failure(target, now)

    # -- commands (MonCommands.h / OSDMonitor command surface) -------------

    async def handle_command(self, cmd: Dict[str, Any]
                             ) -> Tuple[int, Dict[str, Any]]:
        prefix = cmd.get("prefix", "")
        try:
            handler = {
                "osd erasure-code-profile set": self._cmd_profile_set,
                "osd erasure-code-profile get": self._cmd_profile_get,
                "osd pool create": self._cmd_pool_create,
                "osd pool set": self._cmd_pool_set,
                "osd pool mksnap": self._cmd_snap_create,
                "osd pool rmsnap": self._cmd_snap_remove,
                "osd down": self._cmd_osd_down,
                "osd out": self._cmd_osd_out,
                "osd in": self._cmd_osd_in,
                "osd lost": self._cmd_osd_lost,
                "osd pg-upmap-items": self._cmd_pg_upmap_items,
                "osd rm-pg-upmap-items": self._cmd_rm_pg_upmap_items,
                "status": self._cmd_status,
                "health": self._cmd_health,
                "mon stat": self._cmd_mon_stat,
                "config set": self._cmd_config_set,
                "config rm": self._cmd_config_rm,
                "config get": self._cmd_config_get,
                "log last": self._cmd_log_last,
                "crash post": self._cmd_crash_post,
                "crash ls": self._cmd_crash_ls,
                "crash ls-new": self._cmd_crash_ls,
                "crash info": self._cmd_crash_info,
                "crash archive": self._cmd_crash_archive,
                "crash archive-all": self._cmd_crash_archive,
                "crash rm": self._cmd_crash_rm,
            }.get(prefix)
            if handler is None:
                return -22, {"error": f"unknown command {prefix!r}"}
            return await handler(cmd)
        except Exception as e:  # command errors must not kill the mon
            log.exception("mon: command %r failed", prefix)
            return -22, {"error": str(e)}

    async def _cmd_profile_set(self, cmd) -> Tuple[int, Dict[str, Any]]:
        name = cmd["name"]
        profile = dict(cmd["profile"])
        create_erasure_code(dict(profile))  # validate before committing
        async with self._mutation_lock:
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_erasure_code_profiles[name] = profile
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_profile_get(self, cmd) -> Tuple[int, Dict[str, Any]]:
        profile = self.osdmap.erasure_code_profiles.get(cmd["name"])
        if profile is None:
            return -2, {"error": "no such profile"}
        return 0, {"profile": profile}

    async def _cmd_pool_create(self, cmd) -> Tuple[int, Dict[str, Any]]:
        name = cmd["name"]
        if self.osdmap.lookup_pool(name) >= 0:
            return 0, {"pool_id": self.osdmap.lookup_pool(name)}
        pg_num = int(cmd.get("pg_num", 32))
        pool_type = cmd.get("pool_type", "replicated")
        # the WHOLE build runs under the mutation lock: the scratch map
        # allocates the next pool id, and two concurrent creates off
        # the same map would otherwise mint the same id (one pool
        # silently clobbering the other)
        async with self._mutation_lock:
            return await self._pool_create_locked(
                cmd, name, pg_num, pool_type)

    async def _pool_create_locked(self, cmd, name, pg_num, pool_type):
        # stage on a SCRATCH map, then commit the result through an
        # Incremental like every other mutation: the change replays via
        # apply_incremental on every daemon and lands in the inc log
        scratch = OSDMap.decode(self.osdmap.encode())
        if pool_type == "erasure":
            profile_name = cmd.get("erasure_code_profile", "default")
            profile = self.osdmap.erasure_code_profiles.get(profile_name)
            if profile is None:
                return -2, {"error": f"no profile {profile_name!r}"}
            codec = create_erasure_code(dict(profile))
            ruleno = codec.create_rule(f"{name}_rule", scratch.crush)
            pool = scratch.create_pool(
                name, type_=TYPE_ERASURE, size=codec.get_chunk_count(),
                pg_num=pg_num, crush_rule=ruleno,
                erasure_code_profile=profile_name)
        else:
            size = int(cmd.get("size", 3))
            pool = scratch.create_pool(
                name, type_=TYPE_REPLICATED, size=size, pg_num=pg_num)
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pools[pool.id] = pool
        if pool_type == "erasure":
            inc.new_crush = scratch.crush  # carries the new EC rule
        if not await self._commit(inc):
            return -11, {"error": "no quorum; retry"}
        return 0, {"pool_id": pool.id}

    async def _cmd_pool_set(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`osd pool set <name> pg_num <n>` — PG splitting
        (OSDMonitor's pg_num ratchet).  Growth only: live PG merging
        is out of scope (documented deviation; the reference gained
        merge in nautilus)."""
        var = cmd.get("var")
        if var != "pg_num":
            return -22, {"error": f"unsupported pool var {var!r}"}
        try:
            val = int(cmd["val"])
        except (KeyError, ValueError):
            return -22, {"error": "pg_num must be an integer"}
        async with self._mutation_lock:
            pool, inc = self._pool_snap_inc(cmd["name"])
            if pool is None:
                return -2, {"error": "no such pool"}
            if val < pool.pg_num:
                return -22, {"error": "pg_num can only grow (PG merge"
                                      " unsupported)"}
            if val == pool.pg_num:
                return 0, {"pg_num": val}
            pool.pg_num = val
            pool.pgp_num = val
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        log.info("mon.%d: pool %s pg_num -> %d (epoch %d)", self.rank,
                 cmd["name"], val, self.osdmap.epoch)
        return 0, {"pg_num": val, "epoch": self.osdmap.epoch}

    def _pool_snap_inc(self, name: str):
        """Scratch-copy a pool for a snap mutation; returns
        (pool_copy, incremental) or (None, None) when no such pool."""
        pool_id = self.osdmap.lookup_pool(name)
        if pool_id < 0:
            return None, None
        from ceph_tpu.common.encoding import Decoder, Encoder

        enc = Encoder()
        self.osdmap.pools[pool_id].encode(enc)
        from ceph_tpu.osd.osdmap import PgPool

        pool = PgPool.decode(Decoder(enc.to_bytes()))
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pools[pool.id] = pool
        return pool, inc

    async def _cmd_snap_create(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """Self-managed snapshot id allocation (the
        OSDMonitor selfmanaged_snap_create role): bump the pool's
        snap_seq through an Incremental and hand the id back."""
        async with self._mutation_lock:
            pool, inc = self._pool_snap_inc(cmd["name"])
            if pool is None:
                return -2, {"error": "no such pool"}
            pool.snap_seq += 1
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        return 0, {"snap_id": pool.snap_seq}

    async def _cmd_snap_remove(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """Retire a snap id: lands in pool.removed_snaps; primaries trim
        clones when they observe the new map (snap trim role)."""
        async with self._mutation_lock:
            pool, inc = self._pool_snap_inc(cmd["name"])
            if pool is None:
                return -2, {"error": "no such pool"}
            snap_id = int(cmd["snap_id"])
            if snap_id <= 0 or snap_id > pool.snap_seq:
                return -22, {"error": f"bad snap id {snap_id}"}
            if snap_id not in pool.removed_snaps:
                pool.removed_snaps.append(snap_id)
                pool.removed_snaps.sort()
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_osd_down(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        async with self._mutation_lock:
            if self.osdmap.is_up(osd):
                inc = Incremental(epoch=self.osdmap.epoch + 1)
                inc.new_state[osd] = CEPH_OSD_UP
                if not await self._commit(inc):
                    return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_osd_out(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        async with self._mutation_lock:
            if self.osdmap.is_in(osd):
                inc = Incremental(epoch=self.osdmap.epoch + 1)
                inc.new_weight[osd] = 0
                if not await self._commit(inc):
                    return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_osd_in(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        async with self._mutation_lock:
            if not self.osdmap.is_in(osd):
                inc = Incremental(epoch=self.osdmap.epoch + 1)
                inc.new_weight[osd] = CEPH_OSD_IN
                if not await self._commit(inc):
                    return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_osd_lost(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`osd lost <id> --yes-i-really-mean-it`: declare a dead
        OSD's data permanently gone (OSDMonitor.cc `osd lost`).  Marks
        DESTROYED so recovery probes count it as definitively absent —
        the escape hatch that lets unfound-object adjudication finish
        when a source will never return."""
        osd = int(cmd["osd"])
        if not cmd.get("yes_i_really_mean_it"):
            return -1, {"error": "this makes data loss permanent; pass"
                                 " yes_i_really_mean_it"}
        if not self.osdmap.exists(osd):
            return -2, {"error": f"osd.{osd} does not exist"}
        if self.osdmap.is_up(osd):
            return -16, {"error": f"osd.{osd} is up — only a down osd"
                                  " can be declared lost"}
        async with self._mutation_lock:
            if not self.osdmap.is_destroyed(osd):
                inc = Incremental(epoch=self.osdmap.epoch + 1)
                inc.new_state[osd] = CEPH_OSD_DESTROYED  # XOR: set
                if not await self._commit(inc):
                    return -11, {"error": "no quorum; retry"}
        return 0, {"epoch": self.osdmap.epoch}

    async def _cmd_pg_upmap_items(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`osd pg-upmap-items <pool.ps> <from> <to> [...]` — the
        balancer's remap primitive (OSDMonitor.cc `osd pg-upmap-items`
        command).  Validates pairs against the live map before
        committing (maybe_remove_pg_upmaps discipline)."""
        from ceph_tpu.osd.osdmap import PgId

        pool_id, ps = cmd["pgid"].split(".")
        pg = PgId(int(pool_id), int(ps))
        if pg.pool not in self.osdmap.pools or \
                pg.ps >= self.osdmap.pools[pg.pool].pg_num:
            return -2, {"error": f"pg {cmd['pgid']} does not exist"}
        pairs = [(int(a), int(b)) for a, b in cmd["mappings"]]
        if not pairs:
            return -22, {"error": "empty mappings (use"
                                  " rm-pg-upmap-items to clear)"}
        pool = self.osdmap.pools[pg.pool]
        raw, _pps = self.osdmap._pg_to_raw_osds(pool, pg)
        for src, dst in pairs:
            if not (self.osdmap.exists(dst) and self.osdmap.is_in(dst)):
                return -22, {"error": f"target osd.{dst} not in"}
            if src == dst:
                return -22, {"error": "identity mapping"}
            if src not in raw:
                # a src outside the CRUSH raw mapping would commit as
                # permanent dead state _apply_upmap never matches
                # (maybe_remove_pg_upmaps rejection)
                return -22, {"error": f"osd.{src} is not in the raw"
                                      f" mapping of {cmd['pgid']}"}
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pg_upmap_items[pg] = pairs
        async with self._mutation_lock:
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        return 0, {"epoch": self.osdmap.epoch}

    async def _cmd_rm_pg_upmap_items(self, cmd) -> Tuple[int, Dict[str, Any]]:
        from ceph_tpu.osd.osdmap import PgId

        pool_id, ps = cmd["pgid"].split(".")
        pg = PgId(int(pool_id), int(ps))
        if pg not in self.osdmap.pg_upmap_items:
            return 0, {}
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.old_pg_upmap_items.append(pg)
        async with self._mutation_lock:
            if not await self._commit(inc):
                return -11, {"error": "no quorum; retry"}
        return 0, {"epoch": self.osdmap.epoch}

    async def _cmd_config_set(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`ceph config set <who> <name> <value>` (ConfigMonitor):
        who = global | osd | mon | mds | osd.N ... — quorum-committed,
        pushed to every subscriber, durable across restarts."""
        section, name = cmd.get("who", "global"), cmd.get("name")
        if not name:
            return -22, {"error": "missing option name"}
        async with self._mutation_lock:
            ok = await self.paxos.propose(b"C" + json.dumps({
                "section": section, "name": name,
                "value": str(cmd.get("value", ""))}).encode())
            if not ok:
                return -11, {"error": "no quorum; retry"}
        self.clog("INF", f"mon.{self.rank}",
                  f"config set {section}/{name}")
        return 0, {"version": self._config_version}

    async def _cmd_config_rm(self, cmd) -> Tuple[int, Dict[str, Any]]:
        section, name = cmd.get("who", "global"), cmd.get("name")
        if not name:
            return -22, {"error": "missing option name"}
        async with self._mutation_lock:
            ok = await self.paxos.propose(b"C" + json.dumps({
                "section": section, "name": name,
                "value": None}).encode())
            if not ok:
                return -11, {"error": "no quorum; retry"}
        return 0, {"version": self._config_version}

    async def _cmd_config_get(self, cmd) -> Tuple[int, Dict[str, Any]]:
        who = cmd.get("who")
        if who:
            return 0, {"config": self._config_kv.get(who, {}),
                       "version": self._config_version}
        return 0, {"config": self._config_kv,
                   "version": self._config_version}

    async def _cmd_log_last(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`ceph log last [n]` — the cluster log tail."""
        n = int(cmd.get("num", 20))
        return 0, {"entries": list(self._cluster_log)[-n:]}

    # -- crash reports (pybind/mgr/crash + ceph-crash roles) ---------------
    #
    # Daemons post a report when they die unexpectedly; reports are
    # quorum-replicated (tag b"R"), surface as a RECENT_CRASH health
    # warning until archived, and survive mon restarts via the store
    # snapshot.

    CRASH_RECENT_S = 14 * 86400  # RECENT_CRASH window (reference dflt)

    async def _cmd_crash_post(self, cmd) -> Tuple[int, Dict[str, Any]]:
        rep = dict(cmd.get("report") or {})
        if not rep.get("crash_id"):
            return -22, {"error": "report needs a crash_id"}
        rep.setdefault("timestamp", time.time())
        async with self._mutation_lock:
            ok = await self.paxos.propose(b"R" + json.dumps(
                {"op": "post", "report": rep}).encode())
            if not ok:
                return -11, {"error": "no quorum; retry"}
        self.clog("ERR", f"mon.{self.rank}",
                  f"daemon {rep.get('entity', '?')} crashed:"
                  f" {rep['crash_id']}")
        return 0, {"crash_id": rep["crash_id"]}

    async def _cmd_crash_ls(self, cmd) -> Tuple[int, Dict[str, Any]]:
        new_only = cmd.get("prefix") == "crash ls-new"
        out = [{"crash_id": cid,
                "entity": rep.get("entity", ""),
                "timestamp": rep.get("timestamp", 0),
                "archived": bool(rep.get("archived"))}
               for cid, rep in sorted(self._crash.items())
               if not (new_only and rep.get("archived"))]
        return 0, {"crashes": out}

    async def _cmd_crash_info(self, cmd) -> Tuple[int, Dict[str, Any]]:
        rep = self._crash.get(cmd.get("id", ""))
        if rep is None:
            return -2, {"error": "no such crash"}
        return 0, {"report": rep}

    async def _cmd_crash_archive(self, cmd
                                 ) -> Tuple[int, Dict[str, Any]]:
        if cmd.get("prefix") == "crash archive-all":
            doc = {"op": "archive_all"}
        else:
            cid = cmd.get("id", "")
            if cid not in self._crash:
                return -2, {"error": "no such crash"}
            doc = {"op": "archive", "crash_id": cid}
        async with self._mutation_lock:
            if not await self.paxos.propose(
                    b"R" + json.dumps(doc).encode()):
                return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_crash_rm(self, cmd) -> Tuple[int, Dict[str, Any]]:
        cid = cmd.get("id", "")
        if cid not in self._crash:
            return -2, {"error": "no such crash"}
        async with self._mutation_lock:
            if not await self.paxos.propose(b"R" + json.dumps(
                    {"op": "rm", "crash_id": cid}).encode()):
                return -11, {"error": "no quorum; retry"}
        return 0, {}

    async def _cmd_mon_stat(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """Quorum observability (`ceph mon stat` role)."""
        out = {"rank": self.rank, "num_mons": len(self.mon_addrs) or 1,
               "addrs": self.mon_addrs}
        if self.elector is not None:
            out["leader"] = self.elector.leader
            out["election_epoch"] = self.elector.epoch
            out["quorum"] = sorted(self.elector.quorum)
        if self.paxos is not None:
            out["last_committed"] = self.paxos.last_committed
            out["lease_valid"] = self.paxos.lease_valid()
        return 0, out

    async def _cmd_status(self, cmd) -> Tuple[int, Dict[str, Any]]:
        up = self.osdmap.get_up_osds()
        rc, health = await self._cmd_health(cmd)
        return 0, {
            "epoch": self.osdmap.epoch,
            "num_osds": self.osdmap.max_osd,
            "num_up_osds": len(up),
            "num_in_osds": sum(1 for o in range(self.osdmap.max_osd)
                               if self.osdmap.is_in(o)),
            "pools": {p.name: {"id": p.id, "type": p.type,
                               "size": p.size, "pg_num": p.pg_num}
                      for p in self.osdmap.pools.values()},
            "health": health,
        }

    async def _cmd_health(self, cmd) -> Tuple[int, Dict[str, Any]]:
        checks: Dict[str, Dict[str, Any]] = {}
        down = [o for o in range(self.osdmap.max_osd)
                if self.osdmap.exists(o) and self.osdmap.is_down(o)]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in down]}
        degraded = 0
        for pool in self.osdmap.pools.values():
            for ps in range(pool.pg_num):
                from ceph_tpu.osd.osdmap import PgId
                acting, _p = self.osdmap.pg_to_acting_osds(
                    PgId(pool.id, ps))
                alive = [o for o in acting
                         if o >= 0 and self.osdmap.is_up(o)]
                if len(alive) < len([o for o in acting if o >= 0]) or \
                        len(alive) < pool.size:
                    degraded += 1
        if degraded:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{degraded} pgs degraded"}
        recent = [cid for cid, rep in self._crash.items()
                  if not rep.get("archived")
                  and time.time() - rep.get("timestamp", 0)
                  < self.CRASH_RECENT_S]
        if recent:
            checks["RECENT_CRASH"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(recent)} daemons have recently"
                           " crashed",
                "detail": sorted(recent)}
        status = "HEALTH_OK" if not checks else "HEALTH_WARN"
        return 0, {"status": status, "checks": checks}

"""Mini-monitor: the cluster control plane, single-instance.

Reference parity: Monitor + OSDMonitor
(/root/reference/src/mon/Monitor.cc, OSDMonitor.cc) minus Paxos — one
mon instance is authoritative (the reference's single-mon vstart shape);
the PaxosService commit discipline survives as: every map mutation is an
epoch bump whose full map is pushed to all subscribers.

Covered OSDMonitor behaviors:
- OSD lifecycle: MOSDBoot marks up + records the address
  (OSDMonitor::prepare_boot); liveness beacons double as boot.
- Failure adjudication (prepare_failure OSDMonitor.cc:2739,
  check_failure :3156-3185): an OSD is marked down when enough distinct
  reporters (mon_osd_min_down_reporters) have current failure reports
  and the oldest report has aged past an ADAPTIVE grace: base
  osd_heartbeat_grace plus a laggy term from the target's own history
  (halflife-decayed laggy_probability/laggy_interval, the :3180-3185
  math) — flapping OSDs earn longer grace.
- Pool/profile commands (OSDMonitor.cc:7373-7712): erasure-code-profile
  set (validated by instantiating the codec), pool create
  replicated/erasure (EC pools get a rule from the codec like
  create_rule), osd down/out/in, status/health.
- Health checks (mon/health_check.h role): OSD_DOWN / PG_DEGRADED
  summary served by `status` and `health` commands.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    Message,
    MGetMap,
    MMonCommand,
    MMonCommandReply,
    MOSDBoot,
    MOSDFailure,
    MOSDMapMsg,
)
from ceph_tpu.osd.osdmap import (
    CEPH_OSD_DESTROYED,
    CEPH_OSD_IN,
    CEPH_OSD_UP,
    Incremental,
    OSDMap,
    TYPE_ERASURE,
    TYPE_REPLICATED,
)

log = logging.getLogger("mon")

DEFAULTS = {
    "mon_osd_min_down_reporters": 2,
    "osd_heartbeat_grace": 20.0,
    "mon_osd_laggy_halflife": 3600.0,
    "mon_osd_laggy_weight": 0.3,
    "mon_osd_adjust_heartbeat_grace": True,
}


class FailureReport:
    __slots__ = ("first_reported", "last_reported", "failed_for")

    def __init__(self, now: float, failed_for: float):
        self.first_reported = now
        self.last_reported = now
        self.failed_for = failed_for


class MonDaemon:
    """Single authoritative monitor."""

    def __init__(self, num_osds: int, osds_per_host: int = 2,
                 config: Optional[Dict[str, Any]] = None,
                 store=None):
        self.config = dict(DEFAULTS)
        self.config.update(config or {})
        from ceph_tpu.common.auth import parse_secret

        self.msgr = Messenger(
            "mon.0", secret=parse_secret(
                self.config.get("auth_secret")))
        self.msgr.dispatcher = self._dispatch
        # durable state (the MonitorDBStore role,
        # /root/reference/src/mon/MonitorDBStore.h): every commit writes
        # the incremental, the resulting full map, and the auxiliary
        # adjudication state into the KeyValueDB in one transaction, so
        # a mon restart is a reload, not cluster amnesia
        self.store = store
        self._subscribers: List[Connection] = []
        self._inc_log: Dict[int, bytes] = {}
        self._inc_log_max = 1000
        # failure bookkeeping (OSDMonitor::failure_info_t)
        self._failure_reports: Dict[int, Dict[int, FailureReport]] = {}
        # laggy history for adaptive grace (osd_xinfo_t)
        self._laggy_probability: Dict[int, float] = {}
        self._laggy_interval: Dict[int, float] = {}
        self._down_at: Dict[int, float] = {}
        self._up_from: Dict[int, int] = {}  # boot epoch per osd
        self._check_task: Optional[asyncio.Task] = None
        if store is not None and self._load_store():
            return
        self.osdmap = OSDMap.build_simple(num_osds,
                                          osds_per_host=osds_per_host)
        # all OSDs start down (exist + in); boot marks them up
        for osd in range(num_osds):
            self.osdmap.osd_state[osd] &= ~CEPH_OSD_UP
        if store is not None:
            self._persist(None)

    def _load_store(self) -> bool:
        raw = self.store.get("mon", b"osdmap_full")
        if raw is None:
            return False
        self.osdmap = OSDMap.decode(raw)
        # load at most the newest _inc_log_max incrementals (the store
        # is trimmed on commit, but never trust unbounded history)
        loaded = [(int.from_bytes(key, "big"), val)
                  for key, val in self.store.get_iterator("osdmap")]
        for epoch, val in loaded[-self._inc_log_max:]:
            self._inc_log[epoch] = val
        aux = self.store.get("mon", b"aux")
        if aux:
            doc = json.loads(aux.decode())
            self._laggy_probability = {
                int(k): v for k, v in doc["laggy_probability"].items()}
            self._laggy_interval = {
                int(k): v for k, v in doc["laggy_interval"].items()}
            self._up_from = {int(k): v
                             for k, v in doc["up_from"].items()}
        log.info("mon: reloaded epoch %d from store", self.osdmap.epoch)
        return True

    def _persist(self, inc_raw: Optional[bytes]) -> None:
        """One durable transaction per commit (Paxos commit point)."""
        t = self.store.get_transaction()
        if inc_raw is not None:
            t.set("osdmap",
                  self.osdmap.epoch.to_bytes(8, "big"), inc_raw)
            # keep the durable inc log bounded like the in-memory one
            floor = max(0, self.osdmap.epoch - self._inc_log_max)
            t.rm_range_keys("osdmap", (0).to_bytes(8, "big"),
                            floor.to_bytes(8, "big"))
        t.set("mon", b"osdmap_full", self.osdmap.encode())
        t.set("mon", b"aux", json.dumps({
            "laggy_probability": self._laggy_probability,
            "laggy_interval": self._laggy_interval,
            "up_from": self._up_from,
        }).encode())
        self.store.submit_transaction_sync(t)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        addr = await self.msgr.bind(host, port)
        self._check_task = asyncio.get_running_loop().create_task(
            self._check_failures_loop())
        return addr

    async def shutdown(self) -> None:
        if self._check_task is not None:
            self._check_task.cancel()
        await self.msgr.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.addr

    # -- map mutation ------------------------------------------------------

    def _commit(self, inc: Incremental) -> None:
        """Apply an incremental and publish the new epoch (the Paxos
        commit point of the single-instance world)."""
        raw = inc.encode()
        self.osdmap.apply_incremental(inc)
        self._inc_log[inc.epoch] = raw
        while len(self._inc_log) > self._inc_log_max:
            del self._inc_log[min(self._inc_log)]
        if self.store is not None:
            # durable BEFORE published: a subscriber must never see an
            # epoch a restarted mon could forget
            self._persist(raw)
        self._publish()

    def _publish(self) -> None:
        """Push the new epoch to subscribers as the committing
        incremental alone — every subscriber (daemon or client) applies
        epochs in order and pulls missing ranges with MGetMap on a gap,
        so re-encoding and shipping the full map per commit would be
        O(map x subscribers) of pure waste."""
        epoch = self.osdmap.epoch
        inc = self._inc_log.get(epoch)
        if inc is not None:
            msg = MOSDMapMsg(epoch, incrementals=[inc])
        else:  # no incremental for this epoch: fall back to a full map
            msg = MOSDMapMsg(epoch, full_map=self.osdmap.encode())
        for conn in list(self._subscribers):
            if conn.closed:
                self._subscribers.remove(conn)
                continue
            self.msgr._spawn(self._send_quiet(conn, msg))

    async def _send_quiet(self, conn: Connection, msg: Message) -> None:
        try:
            await conn.send(msg)
        except (ConnectionError, OSError):
            pass

    # -- dispatch ----------------------------------------------------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MOSDBoot):
            self._handle_boot(msg)
        elif isinstance(msg, MGetMap):
            if msg.subscribe and conn not in self._subscribers:
                self._subscribers.append(conn)
            cur = self.osdmap.epoch
            since = msg.since_epoch
            if since and all(e in self._inc_log
                             for e in range(since + 1, cur + 1)):
                await conn.send(MOSDMapMsg(
                    cur, incrementals=[self._inc_log[e]
                                       for e in range(since + 1,
                                                      cur + 1)]))
            else:
                await conn.send(MOSDMapMsg(
                    cur, full_map=self.osdmap.encode(),
                    gap_unfillable=bool(since)))
        elif isinstance(msg, MOSDFailure):
            self._handle_failure(msg)
        elif isinstance(msg, MMonCommand):
            rc, out = self.handle_command(msg.cmd)
            await conn.send(MMonCommandReply(msg.tid, rc, out))

    # -- boot / failure ----------------------------------------------------

    def _handle_boot(self, msg: MOSDBoot) -> None:
        osd = msg.osd
        if not (0 <= osd < self.osdmap.max_osd):
            return
        now = time.monotonic()
        # returning after a mon-ordered down: update laggy history
        # (OSDMonitor laggy tracking feeding the adaptive grace)
        down_at = self._down_at.pop(osd, None)
        if down_at is not None:
            halflife = self.config["mon_osd_laggy_halflife"]
            weight = self.config["mon_osd_laggy_weight"]
            interval = now - down_at
            decay = 0.5 ** (interval / halflife)
            self._laggy_probability[osd] = min(
                1.0, self._laggy_probability.get(osd, 0.0) * decay
                + weight)
            self._laggy_interval[osd] = (
                self._laggy_interval.get(osd, 0.0) * decay
                + interval * weight)
        self._failure_reports.pop(osd, None)
        if self.osdmap.is_up(osd) and \
                self.osdmap.osd_addrs.get(osd) == msg.addr:
            return
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_up_osds[osd] = msg.addr
        if not self.osdmap.is_in(osd):
            inc.new_weight[osd] = CEPH_OSD_IN
        if self.osdmap.is_destroyed(osd):
            # a lost OSD that comes back rejoins with normal probe
            # semantics (its declared-gone window is over)
            inc.new_state[osd] = CEPH_OSD_DESTROYED  # XOR: clear
        self._commit(inc)
        self._up_from[osd] = self.osdmap.epoch
        log.info("mon: osd.%d booted at %s (epoch %d)", osd, msg.addr,
                 self.osdmap.epoch)

    def _handle_failure(self, msg: MOSDFailure) -> None:
        target = msg.target_osd
        if not self.osdmap.is_up(target):
            return
        # a report from before the target's current boot is about a
        # previous incarnation (OSDMonitor::prepare_failure epoch check)
        if msg.epoch < self._up_from.get(target, 0):
            return
        reports = self._failure_reports.setdefault(target, {})
        now = time.monotonic()
        report = reports.get(msg.reporter)
        if report is None:
            reports[msg.reporter] = FailureReport(now, msg.failed_for)
        else:
            report.last_reported = now
            report.failed_for = msg.failed_for
        self._check_failure(target, now)

    def _grace(self, target: int) -> float:
        """Adaptive grace (OSDMonitor.cc:3180-3185): base + decayed
        laggy_probability * laggy_interval."""
        grace = float(self.config["osd_heartbeat_grace"])
        if self.config["mon_osd_adjust_heartbeat_grace"]:
            prob = self._laggy_probability.get(target, 0.0)
            interval = self._laggy_interval.get(target, 0.0)
            if prob > 0.05 and interval > 0:
                grace += prob * interval
        return grace

    def _check_failure(self, target: int, now: float) -> None:
        reports = self._failure_reports.get(target, {})
        if len(reports) < int(self.config["mon_osd_min_down_reporters"]):
            return
        oldest = min(r.first_reported for r in reports.values())
        max_failed = max(r.failed_for for r in reports.values())
        if max(now - oldest, max_failed) < self._grace(target):
            return
        log.info("mon: marking osd.%d down (%d reporters, grace %.1fs)",
                 target, len(reports), self._grace(target))
        self._failure_reports.pop(target, None)
        self._down_at[target] = now
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_state[target] = CEPH_OSD_UP  # XOR: up -> down
        self._commit(inc)

    async def _check_failures_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            for target in list(self._failure_reports):
                self._check_failure(target, now)

    # -- commands (MonCommands.h / OSDMonitor command surface) -------------

    def handle_command(self, cmd: Dict[str, Any]
                       ) -> Tuple[int, Dict[str, Any]]:
        prefix = cmd.get("prefix", "")
        try:
            handler = {
                "osd erasure-code-profile set": self._cmd_profile_set,
                "osd erasure-code-profile get": self._cmd_profile_get,
                "osd pool create": self._cmd_pool_create,
                "osd pool mksnap": self._cmd_snap_create,
                "osd pool rmsnap": self._cmd_snap_remove,
                "osd down": self._cmd_osd_down,
                "osd out": self._cmd_osd_out,
                "osd in": self._cmd_osd_in,
                "osd lost": self._cmd_osd_lost,
                "osd pg-upmap-items": self._cmd_pg_upmap_items,
                "osd rm-pg-upmap-items": self._cmd_rm_pg_upmap_items,
                "status": self._cmd_status,
                "health": self._cmd_health,
            }.get(prefix)
            if handler is None:
                return -22, {"error": f"unknown command {prefix!r}"}
            return handler(cmd)
        except Exception as e:  # command errors must not kill the mon
            log.exception("mon: command %r failed", prefix)
            return -22, {"error": str(e)}

    def _cmd_profile_set(self, cmd) -> Tuple[int, Dict[str, Any]]:
        name = cmd["name"]
        profile = dict(cmd["profile"])
        create_erasure_code(dict(profile))  # validate before committing
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_erasure_code_profiles[name] = profile
        self._commit(inc)
        return 0, {}

    def _cmd_profile_get(self, cmd) -> Tuple[int, Dict[str, Any]]:
        profile = self.osdmap.erasure_code_profiles.get(cmd["name"])
        if profile is None:
            return -2, {"error": "no such profile"}
        return 0, {"profile": profile}

    def _cmd_pool_create(self, cmd) -> Tuple[int, Dict[str, Any]]:
        name = cmd["name"]
        if self.osdmap.lookup_pool(name) >= 0:
            return 0, {"pool_id": self.osdmap.lookup_pool(name)}
        pg_num = int(cmd.get("pg_num", 32))
        pool_type = cmd.get("pool_type", "replicated")
        # stage on a SCRATCH map, then commit the result through an
        # Incremental like every other mutation: the change replays via
        # apply_incremental on every daemon and lands in the inc log
        scratch = OSDMap.decode(self.osdmap.encode())
        if pool_type == "erasure":
            profile_name = cmd.get("erasure_code_profile", "default")
            profile = self.osdmap.erasure_code_profiles.get(profile_name)
            if profile is None:
                return -2, {"error": f"no profile {profile_name!r}"}
            codec = create_erasure_code(dict(profile))
            ruleno = codec.create_rule(f"{name}_rule", scratch.crush)
            pool = scratch.create_pool(
                name, type_=TYPE_ERASURE, size=codec.get_chunk_count(),
                pg_num=pg_num, crush_rule=ruleno,
                erasure_code_profile=profile_name)
        else:
            size = int(cmd.get("size", 3))
            pool = scratch.create_pool(
                name, type_=TYPE_REPLICATED, size=size, pg_num=pg_num)
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pools[pool.id] = pool
        if pool_type == "erasure":
            inc.new_crush = scratch.crush  # carries the new EC rule
        self._commit(inc)
        return 0, {"pool_id": pool.id}

    def _pool_snap_inc(self, name: str):
        """Scratch-copy a pool for a snap mutation; returns
        (pool_copy, incremental) or (None, None) when no such pool."""
        pool_id = self.osdmap.lookup_pool(name)
        if pool_id < 0:
            return None, None
        from ceph_tpu.common.encoding import Decoder, Encoder

        enc = Encoder()
        self.osdmap.pools[pool_id].encode(enc)
        from ceph_tpu.osd.osdmap import PgPool

        pool = PgPool.decode(Decoder(enc.to_bytes()))
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pools[pool.id] = pool
        return pool, inc

    def _cmd_snap_create(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """Self-managed snapshot id allocation (the
        OSDMonitor selfmanaged_snap_create role): bump the pool's
        snap_seq through an Incremental and hand the id back."""
        pool, inc = self._pool_snap_inc(cmd["name"])
        if pool is None:
            return -2, {"error": "no such pool"}
        pool.snap_seq += 1
        self._commit(inc)
        return 0, {"snap_id": pool.snap_seq}

    def _cmd_snap_remove(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """Retire a snap id: lands in pool.removed_snaps; primaries trim
        clones when they observe the new map (snap trim role)."""
        pool, inc = self._pool_snap_inc(cmd["name"])
        if pool is None:
            return -2, {"error": "no such pool"}
        snap_id = int(cmd["snap_id"])
        if snap_id <= 0 or snap_id > pool.snap_seq:
            return -22, {"error": f"bad snap id {snap_id}"}
        if snap_id not in pool.removed_snaps:
            pool.removed_snaps.append(snap_id)
            pool.removed_snaps.sort()
        self._commit(inc)
        return 0, {}

    def _cmd_osd_down(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        if self.osdmap.is_up(osd):
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_state[osd] = CEPH_OSD_UP
            self._commit(inc)
        return 0, {}

    def _cmd_osd_out(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        if self.osdmap.is_in(osd):
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_weight[osd] = 0
            self._commit(inc)
        return 0, {}

    def _cmd_osd_in(self, cmd) -> Tuple[int, Dict[str, Any]]:
        osd = int(cmd["osd"])
        if not self.osdmap.is_in(osd):
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_weight[osd] = CEPH_OSD_IN
            self._commit(inc)
        return 0, {}

    def _cmd_osd_lost(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`osd lost <id> --yes-i-really-mean-it`: declare a dead
        OSD's data permanently gone (OSDMonitor.cc `osd lost`).  Marks
        DESTROYED so recovery probes count it as definitively absent —
        the escape hatch that lets unfound-object adjudication finish
        when a source will never return."""
        osd = int(cmd["osd"])
        if not cmd.get("yes_i_really_mean_it"):
            return -1, {"error": "this makes data loss permanent; pass"
                                 " yes_i_really_mean_it"}
        if not self.osdmap.exists(osd):
            return -2, {"error": f"osd.{osd} does not exist"}
        if self.osdmap.is_up(osd):
            return -16, {"error": f"osd.{osd} is up — only a down osd"
                                  " can be declared lost"}
        if not self.osdmap.is_destroyed(osd):
            inc = Incremental(epoch=self.osdmap.epoch + 1)
            inc.new_state[osd] = CEPH_OSD_DESTROYED  # XOR: set
            self._commit(inc)
        return 0, {"epoch": self.osdmap.epoch}

    def _cmd_pg_upmap_items(self, cmd) -> Tuple[int, Dict[str, Any]]:
        """`osd pg-upmap-items <pool.ps> <from> <to> [...]` — the
        balancer's remap primitive (OSDMonitor.cc `osd pg-upmap-items`
        command).  Validates pairs against the live map before
        committing (maybe_remove_pg_upmaps discipline)."""
        from ceph_tpu.osd.osdmap import PgId

        pool_id, ps = cmd["pgid"].split(".")
        pg = PgId(int(pool_id), int(ps))
        if pg.pool not in self.osdmap.pools or \
                pg.ps >= self.osdmap.pools[pg.pool].pg_num:
            return -2, {"error": f"pg {cmd['pgid']} does not exist"}
        pairs = [(int(a), int(b)) for a, b in cmd["mappings"]]
        if not pairs:
            return -22, {"error": "empty mappings (use"
                                  " rm-pg-upmap-items to clear)"}
        pool = self.osdmap.pools[pg.pool]
        raw, _pps = self.osdmap._pg_to_raw_osds(pool, pg)
        for src, dst in pairs:
            if not (self.osdmap.exists(dst) and self.osdmap.is_in(dst)):
                return -22, {"error": f"target osd.{dst} not in"}
            if src == dst:
                return -22, {"error": "identity mapping"}
            if src not in raw:
                # a src outside the CRUSH raw mapping would commit as
                # permanent dead state _apply_upmap never matches
                # (maybe_remove_pg_upmaps rejection)
                return -22, {"error": f"osd.{src} is not in the raw"
                                      f" mapping of {cmd['pgid']}"}
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.new_pg_upmap_items[pg] = pairs
        self._commit(inc)
        return 0, {"epoch": self.osdmap.epoch}

    def _cmd_rm_pg_upmap_items(self, cmd) -> Tuple[int, Dict[str, Any]]:
        from ceph_tpu.osd.osdmap import PgId

        pool_id, ps = cmd["pgid"].split(".")
        pg = PgId(int(pool_id), int(ps))
        if pg not in self.osdmap.pg_upmap_items:
            return 0, {}
        inc = Incremental(epoch=self.osdmap.epoch + 1)
        inc.old_pg_upmap_items.append(pg)
        self._commit(inc)
        return 0, {"epoch": self.osdmap.epoch}

    def _cmd_status(self, cmd) -> Tuple[int, Dict[str, Any]]:
        up = self.osdmap.get_up_osds()
        rc, health = self._cmd_health(cmd)
        return 0, {
            "epoch": self.osdmap.epoch,
            "num_osds": self.osdmap.max_osd,
            "num_up_osds": len(up),
            "num_in_osds": sum(1 for o in range(self.osdmap.max_osd)
                               if self.osdmap.is_in(o)),
            "pools": {p.name: {"id": p.id, "type": p.type,
                               "size": p.size, "pg_num": p.pg_num}
                      for p in self.osdmap.pools.values()},
            "health": health,
        }

    def _cmd_health(self, cmd) -> Tuple[int, Dict[str, Any]]:
        checks: Dict[str, Dict[str, Any]] = {}
        down = [o for o in range(self.osdmap.max_osd)
                if self.osdmap.exists(o) and self.osdmap.is_down(o)]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in down]}
        degraded = 0
        for pool in self.osdmap.pools.values():
            for ps in range(pool.pg_num):
                from ceph_tpu.osd.osdmap import PgId
                acting, _p = self.osdmap.pg_to_acting_osds(
                    PgId(pool.id, ps))
                alive = [o for o in acting
                         if o >= 0 and self.osdmap.is_up(o)]
                if len(alive) < len([o for o in acting if o >= 0]) or \
                        len(alive) < pool.size:
                    degraded += 1
        if degraded:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{degraded} pgs degraded"}
        status = "HEALTH_OK" if not checks else "HEALTH_WARN"
        return 0, {"status": status, "checks": checks}

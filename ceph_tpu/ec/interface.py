"""Erasure-code interface, mirroring the reference's capability surface.

Reference seam: ceph::ErasureCodeInterface
(/root/reference/src/erasure-code/ErasureCodeInterface.h:170-462) and the
shared base class ceph::ErasureCode
(/root/reference/src/erasure-code/ErasureCode.cc).  Behavioral parity points:

- profiles are string->string maps; unknown keys are preserved and echoed;
- object -> chunk layout: chunk B/C of the padded object at offset B%C
  (ErasureCodeInterface.h:39-78);
- padding: the object is zero-padded to a multiple of the technique
  alignment; trailing data chunks may be entirely padding
  (ErasureCode.cc:151-186 encode_prepare);
- chunk remapping via the profile's `mapping=DD_D...` string
  (ErasureCode.cc:261-280 to_mapping);
- minimum_to_decode: want if available, else first k available chunks
  (ErasureCode.cc:103-137);
- sanity: k >= 2, m >= 1 (ErasureCode.cc:85-96).

Buffers here are `bytes`/numpy uint8; the reference's bufferlist zero-copy
chains are replaced by device arrays — alignment for SIMD becomes alignment
for TPU lanes, handled inside the kernels.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional, Set

ErasureCodeProfile = Dict[str, str]

SIMD_ALIGN = 32  # reference memory alignment; kept for layout-parity math


def _freeze(buf) -> memoryview:
    """Read-only zero-copy view of a LOCALLY-OWNED bytearray the
    caller will never touch again (the encode/decode scratch buffers
    below): the hot-path-copy discipline's replacement for the old
    per-chunk bytes() materialization, which re-copied every object
    once more on its way out."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    return mv.toreadonly()


class ErasureCodeError(Exception):
    def __init__(self, errno_: int, msg: str):
        super().__init__(msg)
        self.errno = errno_


def to_int(name: str, profile: ErasureCodeProfile, default: str) -> int:
    if not profile.get(name):
        profile[name] = default
    try:
        return int(profile[name])
    except ValueError:
        raise ErasureCodeError(22, f"could not convert {name}={profile[name]} to int")


def to_bool(name: str, profile: ErasureCodeProfile, default: str) -> bool:
    if not profile.get(name):
        profile[name] = default
    return profile[name].lower() in ("true", "1", "yes")


class ErasureCode:
    """Base codec: profile plumbing, chunk layout, padding, decode scaffolding."""

    def __init__(self) -> None:
        self.k = 0
        self.m = 0
        self.chunk_mapping: List[int] = []
        self._profile: ErasureCodeProfile = {}
        self.rule_root = "default"
        self.rule_failure_domain = "host"
        self.rule_device_class = ""

    # -- profile / init ---------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = profile.setdefault("crush-root", "default")
        self.rule_failure_domain = profile.setdefault("crush-failure-domain", "host")
        self.rule_device_class = profile.setdefault("crush-device-class", "")
        self._to_mapping(profile)
        self._profile = profile

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def _to_mapping(self, profile: ErasureCodeProfile) -> None:
        mapping = profile.get("mapping")
        if mapping:
            data, coding = [], []
            for position, ch in enumerate(mapping):
                (data if ch == "D" else coding).append(position)
            self.chunk_mapping = data + coding

    @staticmethod
    def sanity_check_k_m(k: int, m: int) -> None:
        if k < 2:
            raise ErasureCodeError(22, f"k={k} must be >= 2")
        if m < 1:
            raise ErasureCodeError(22, f"m={m} must be >= 1")

    # -- geometry ---------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_sub_chunk_count(self) -> int:
        return 1

    def supports_fractional_repair(self) -> bool:
        """True when the codec can rebuild ONE lost chunk from
        sub-chunk fractions of d >= k helpers (the regenerating-code
        repair API: minimum_to_repair / repair_project / repair)
        instead of k full chunks.  The recovery engine gates its
        repair-aware path on this; everything else keeps the classic
        k-read reconstruct."""
        return False

    def get_alignment(self) -> int:
        raise NotImplementedError

    def get_chunk_size(self, object_size: int) -> int:
        """Padded-object chunk size (ErasureCodeJerasure::get_chunk_size)."""
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if i < len(self.chunk_mapping) else i

    def get_chunk_mapping(self) -> List[int]:
        return self.chunk_mapping

    # -- decode planning --------------------------------------------------

    def _minimum_to_decode(self, want_to_read: Set[int],
                           available_chunks: Set[int]) -> Set[int]:
        if want_to_read <= available_chunks:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available_chunks) < k:
            raise ErasureCodeError(5, "not enough chunks to decode")
        return set(sorted(available_chunks)[:k])

    def minimum_to_decode(self, want_to_read: Set[int],
                          available_chunks: Set[int]
                          ) -> Dict[int, List[tuple]]:
        ids = self._minimum_to_decode(want_to_read, available_chunks)
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in ids}

    def minimum_to_decode_with_cost(self, want_to_read: Set[int],
                                    available: Mapping[int, int]) -> Set[int]:
        return self._minimum_to_decode(want_to_read, set(available))

    # -- encode / decode --------------------------------------------------

    def encode_prepare(self, raw: bytes) -> Dict[int, bytearray]:
        """Split + zero-pad into k data chunks, allocate m parity chunks."""
        k, m = self.k, self.m
        blocksize = self.get_chunk_size(len(raw))
        padded_chunks = k - len(raw) // blocksize
        encoded: Dict[int, bytearray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = bytearray(
                raw[i * blocksize : (i + 1) * blocksize])
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = bytearray(blocksize)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize :]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = bytearray(blocksize)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = bytearray(blocksize)
        return encoded

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        raise NotImplementedError

    def encode(self, want_to_encode: Iterable[int],
               data: bytes) -> Dict[int, bytes]:
        want = set(want_to_encode)
        encoded = self.encode_prepare(data)
        self.encode_chunks(want, encoded)
        # chunks leave as frozen views of the locally-built buffers
        # (nothing holds the bytearrays after this return)
        return {i: _freeze(b) for i, b in encoded.items()
                if i in want}

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        raise NotImplementedError

    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, bytes],
               chunk_size: Optional[int] = None) -> Dict[int, bytes]:
        want = set(want_to_read)
        if want <= set(chunks):
            # nothing to decode: pass the caller's buffers through
            # (immutable already, or a view the caller owns — the
            # msgr->OSD path feeds immutable frame views here)
            return {i: chunks[i] if isinstance(chunks[i], bytes)
                    else _freeze(chunks[i]) for i in want}
        if not chunks:
            raise ErasureCodeError(5, "no chunks to decode from")
        blocksize = len(next(iter(chunks.values())))
        decoded: Dict[int, bytearray] = {}
        for i in range(self.k + self.m):
            if i in chunks:
                decoded[i] = bytearray(chunks[i])
            else:
                decoded[i] = bytearray(blocksize)
        self.decode_chunks(want, chunks, decoded)
        return {i: _freeze(decoded[i]) for i in want}

    def decode_concat(self, chunks: Mapping[int, bytes]) -> bytes:
        """Reassemble data payload in chunk_mapping order (decode_concat)."""
        want = {self.chunk_index(i) for i in range(self.get_data_chunk_count())}
        decoded = self.decode(want, chunks)
        out = bytearray()
        for i in range(self.get_data_chunk_count()):
            out += decoded[self.chunk_index(i)]
        return _freeze(out)

    # -- CRUSH integration (populated once crush module lands) -----------

    def create_rule(self, name: str, crush) -> int:
        return crush.add_simple_rule(
            name, self.rule_root, self.rule_failure_domain,
            self.rule_device_class, "indep", pool_type="erasure")

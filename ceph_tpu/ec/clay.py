"""CLAY — coupled-layer MSR regenerating code (k, m, d).

Reference parity: the clay plugin
(/root/reference/src/erasure-code/clay/ErasureCodeClay.{h,cc}), after
Vajha et al., "Clay Codes" (FAST'18):

- nodes live on a (q, t) grid, q = d-k+1, t = (k+m+nu)/q, with nu zero
  "shortening" nodes so q | (k+m+nu); each chunk splits into
  sub_chunk_no = q^t sub-chunks, one per plane z in [0, q^t)
  (parse :188-302);
- a scalar MDS code (here the TPU ec_jax codec) encodes *uncoupled* planes;
  coupled chunks C relate to uncoupled U through a pairwise (2,2) MDS
  transform on symmetric node pairs (the PFT, pft.erasure_code in the
  reference; cached 2x2 GF solves here);
- encode = decode of the parity nodes from the data nodes
  (encode_chunks :129-157); full decode walks planes in
  intersection-score order, converting coupled->uncoupled, MDS-decoding
  each plane, and recovering coupled values (decode_layered :647-712,
  decode_erasures :714-741);
- single-node repair reads only sub_chunk_no/q sub-chunks from each of d
  helpers (is_repair :304-323, minimum_to_repair :325-361,
  get_repair_subchunks :363-377, repair_one_lost_chunk :462-645) — the
  MSR bandwidth optimality that is CLAY's point.

Sub-chunked reads surface through minimum_to_decode's
(offset, count) sub-chunk ranges, exactly like the reference interface
(ErasureCodeInterface.h minimum_to_decode on array codes).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Set, Tuple

import numpy as np

from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, to_int
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import gf


class ErasureCodeClay(ErasureCode):
    DEFAULT_K, DEFAULT_M = 4, 2

    def __init__(self) -> None:
        super().__init__()
        self.d = 0
        self.w = 8
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds: Optional[ErasureCode] = None
        self.pft_matrix: Optional[np.ndarray] = None  # (2,2) scalar code
        self._pft_inv_cache: Dict[Tuple[int, int], np.ndarray] = {}

    # -- init -------------------------------------------------------------

    def init(self, profile: Dict[str, str]) -> None:
        self.k = to_int("k", profile, str(self.DEFAULT_K))
        self.m = to_int("m", profile, str(self.DEFAULT_M))
        self.sanity_check_k_m(self.k, self.m)
        self.d = to_int("d", profile, str(self.k + self.m - 1))
        if not (self.k <= self.d <= self.k + self.m - 1):
            raise ErasureCodeError(
                22, f"value of d {self.d} must be within"
                f" [{self.k},{self.k + self.m - 1}]")

        scalar_mds = profile.get("scalar_mds") or "jerasure"
        if scalar_mds not in ("jerasure", "isa", "shec"):
            raise ErasureCodeError(
                22, f"scalar_mds {scalar_mds} is not currently supported,"
                " use one of 'jerasure', 'isa', 'shec'")
        technique = profile.get("technique") or (
            "reed_sol_van" if scalar_mds in ("jerasure", "isa") else "single")

        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) % self.q
        if self.k + self.m + self.nu > 254:
            raise ErasureCodeError(22, "k + m + nu must be <= 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t

        from ceph_tpu.ec.registry import ErasureCodePluginRegistry

        mds_profile = {"plugin": scalar_mds, "technique": technique,
                       "k": str(self.k + self.nu), "m": str(self.m),
                       "w": "8"}
        if scalar_mds == "shec":
            mds_profile["c"] = "2"
        self.mds = ErasureCodePluginRegistry.instance().factory(
            scalar_mds, mds_profile)
        self.pft_matrix = rs.reed_sol_van_matrix(2, 2)
        self._pft_inv_cache.clear()
        super().init(profile)

    # -- geometry ---------------------------------------------------------

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_alignment(self) -> int:
        # sub_chunk_no * k * (scalar-code alignment unit)
        # (ErasureCodeClay::get_chunk_size :— pft chunk of a 1-byte object)
        return self.sub_chunk_no * self.k * 32

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        padded = -(-object_size // alignment) * alignment
        return padded // self.k

    # -- plane helpers ----------------------------------------------------

    def _plane_vector(self, z: int) -> List[int]:
        out = [0] * self.t
        for i in range(self.t):
            out[self.t - 1 - i] = z % self.q
            z //= self.q
        return out

    def _z_sw(self, x: int, y: int, z: int, z_vec: List[int]) -> int:
        return z + (x - z_vec[y]) * self.q ** (self.t - 1 - y)

    # -- pairwise (2,2) transform -----------------------------------------
    #
    # Canonical 4-row generator over the coupled pair (A, B):
    # rows 0,1 = identity (the coupled values), rows 2,3 = the scalar
    # (2,2) parity rows (the uncoupled values).  Slot 0/2 belong to the
    # pair member with the LARGER x coordinate (the i0/i2 swap in the
    # reference).

    def _pft_rows(self) -> np.ndarray:
        ident = np.eye(2, dtype=np.uint8)
        return np.concatenate([ident, self.pft_matrix], axis=0)

    def _pft_solve(self, known: Dict[int, np.ndarray],
                   want: List[int]) -> Dict[int, np.ndarray]:
        rows = self._pft_rows()
        ki = tuple(sorted(known))[:2]
        inv = self._pft_inv_cache.get(ki)
        if inv is None:
            inv = gf.gf_invert_matrix(rows[list(ki)])
            self._pft_inv_cache[ki] = inv
        vals = np.stack([known[i] for i in ki])
        ab = gf.gf_matmul_ref(inv, vals)
        out = gf.gf_matmul_ref(rows[list(want)], ab)
        return {w: out[i] for i, w in enumerate(want)}

    def _pair_slots(self, x: int, y: int, z: int, z_vec: List[int]):
        """-> ((node_xy, z), (node_sw, z_sw), swapped) with slot order."""
        node_xy = y * self.q + x
        node_sw = y * self.q + z_vec[y]
        z_sw = self._z_sw(x, y, z, z_vec)
        swapped = z_vec[y] > x  # node_xy takes slots 1/3 instead of 0/2
        return node_xy, node_sw, z_sw, swapped

    # -- coupled <-> uncoupled conversions (per plane) --------------------

    def _uncoupled_from_coupled(self, C, U, x, y, z, z_vec):
        node_xy, node_sw, z_sw, swapped = self._pair_slots(x, y, z, z_vec)
        i0, i2 = (1, 3) if swapped else (0, 2)
        i1, i3 = 1 - i0, 5 - i2
        out = self._pft_solve(
            {i0: C[node_xy][z], i1: C[node_sw][z_sw]}, [i2, i3])
        U[node_xy][z] = out[i2]
        U[node_sw][z_sw] = out[i3]

    def _coupled_from_uncoupled(self, C, U, x, y, z, z_vec):
        node_xy, node_sw, z_sw, _sw = self._pair_slots(x, y, z, z_vec)
        # only called with z_vec[y] < x: node_xy is slot 0
        out = self._pft_solve(
            {2: U[node_xy][z], 3: U[node_sw][z_sw]}, [0, 1])
        C[node_xy][z] = out[0]
        C[node_sw][z_sw] = out[1]

    def _recover_type1(self, C, U, x, y, z, z_vec):
        node_xy, node_sw, z_sw, swapped = self._pair_slots(x, y, z, z_vec)
        i0, i2 = (1, 3) if swapped else (0, 2)
        i1 = 1 - i0
        out = self._pft_solve(
            {i1: C[node_sw][z_sw], i2: U[node_xy][z]}, [i0])
        C[node_xy][z] = out[i0]

    # -- MDS over uncoupled planes ----------------------------------------

    def _decode_uncoupled(self, erasures: Set[int], z: int, U) -> None:
        """MDS-decode plane z of U for the erased nodes."""
        self._decode_uncoupled_planes(erasures, [z], U)

    def _decode_uncoupled_planes(self, erasures: Set[int],
                                 planes: List[int], U) -> None:
        """Batch-decode several planes sharing one erasure set: one decode
        matrix, one (B, k, S) device dispatch (the reference loops planes
        one decode_chunks call each, ErasureCodeClay.cc:743-761)."""
        from ceph_tpu.ec.jax_plugin import ErasureCodeJax

        n = self.q * self.t
        if isinstance(self.mds, ErasureCodeJax):
            have = tuple(i for i in range(n) if i not in erasures)[
                :self.mds.k]
            erased = tuple(sorted(erasures))
            survivors = np.stack(
                [[U[i][z] for i in have] for z in planes])
            out = self.mds.decode_batch(have, erased, survivors)
            for b, z in enumerate(planes):
                for row, e in enumerate(erased):
                    U[e][z] = out[b, row]
            return
        # generic scalar codec: per-plane through the bytes interface
        # (plane rows pass as contiguous views; bytearray() owns the
        # one copy the scratch buffers genuinely need)
        for z in planes:
            sc = U[0].shape[1]
            chunks = {i: np.ascontiguousarray(U[i][z]).data
                      for i in range(n) if i not in erasures}
            decoded = {i: bytearray(np.ascontiguousarray(U[i][z]))
                       for i in range(n)}
            self.mds.decode_chunks(set(erasures), chunks, decoded)
            for i in erasures:
                U[i][z] = np.frombuffer(decoded[i],
                                        dtype=np.uint8)[:sc]

    # -- layered decode (the heart; encode routes through it too) ---------

    def _decode_layered(self, erased_chunks: Set[int], C: Dict[int, np.ndarray]):
        q, t = self.q, self.t
        erased = set(erased_chunks)
        for i in range(self.k + self.nu, q * t):
            if len(erased) >= self.m:
                break
            erased.add(i)
        if len(erased) != self.m:
            raise ErasureCodeError(
                5, f"{len(erased_chunks)} erasures exceed m={self.m}")

        sc = C[0].shape[1]
        U = {i: np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
             for i in range(q * t)}

        order = [0] * self.sub_chunk_no
        for z in range(self.sub_chunk_no):
            z_vec = self._plane_vector(z)
            order[z] = sum(1 for i in erased if i % q == z_vec[i // q])
        max_iscore = len({i // q for i in erased})

        for iscore in range(max_iscore + 1):
            planes = [z for z in range(self.sub_chunk_no)
                      if order[z] == iscore]
            if not planes:
                continue
            for z in planes:
                self._fill_uncoupled(erased, z, C, U)
            self._decode_uncoupled_planes(erased, planes, U)
            for z in planes:
                z_vec = self._plane_vector(z)
                for node_xy in erased:
                    x, y = node_xy % q, node_xy // q
                    node_sw = y * q + z_vec[y]
                    if z_vec[y] != x:
                        if node_sw not in erased:
                            self._recover_type1(C, U, x, y, z, z_vec)
                        elif z_vec[y] < x:
                            self._coupled_from_uncoupled(C, U, x, y, z, z_vec)
                    else:  # hole-dot pair: C == U
                        C[node_xy][z] = U[node_xy][z]

    def _fill_uncoupled(self, erased: Set[int], z: int, C, U) -> None:
        """Coupled -> uncoupled for the known nodes of one plane."""
        q, t = self.q, self.t
        z_vec = self._plane_vector(z)
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + z_vec[y]
                if node_xy in erased:
                    continue
                if z_vec[y] < x:
                    self._uncoupled_from_coupled(C, U, x, y, z, z_vec)
                elif z_vec[y] == x:
                    U[node_xy][z] = C[node_xy][z]
                else:
                    if node_sw in erased:
                        self._uncoupled_from_coupled(C, U, x, y, z, z_vec)

    def _decode_erasures(self, erased: Set[int], z: int, C, U) -> None:
        self._fill_uncoupled(erased, z, C, U)
        self._decode_uncoupled(erased, z, U)

    # -- interface: encode / decode ---------------------------------------

    def _node_arrays(self, encoded: Mapping[int, bytearray]) -> Dict[int, np.ndarray]:
        """Chunk buffers -> per-node (sub_chunk_no, sc) plane arrays, with
        nu zero shortening nodes spliced in at [k, k+nu)."""
        chunk_size = len(encoded[0])
        if chunk_size % self.sub_chunk_no:
            raise ErasureCodeError(
                22, f"chunk size {chunk_size} not divisible by"
                f" sub_chunk_no {self.sub_chunk_no}")
        sc = chunk_size // self.sub_chunk_no
        C: Dict[int, np.ndarray] = {}
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            # ONE copy (the .copy(): C is a mutable working set); the
            # old bytes() wrapper paid a second whole-chunk copy first
            C[node] = np.frombuffer(
                encoded[i], dtype=np.uint8).reshape(
                    self.sub_chunk_no, sc).copy()
        for i in range(self.k, self.k + self.nu):
            C[i] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        return C

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        C = self._node_arrays(encoded)
        parity_nodes = {i + self.nu for i in
                        range(self.k, self.k + self.m)}
        self._decode_layered(parity_nodes, C)
        for i in range(self.k, self.k + self.m):
            encoded[i][:] = np.ascontiguousarray(
                C[i + self.nu]).reshape(-1).data

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        erasures = {(i if i < self.k else i + self.nu)
                    for i in range(self.k + self.m) if i not in chunks}
        C = self._node_arrays(decoded)
        self._decode_layered(erasures, C)
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            decoded[i][:] = np.ascontiguousarray(
                C[node]).reshape(-1).data

    # -- repair (the MSR selling point) -----------------------------------

    def is_repair(self, want_to_read: Set[int],
                  available_chunks: Set[int]) -> bool:
        if set(want_to_read) <= set(available_chunks):
            return False
        if len(want_to_read) > 1:
            return False
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and 0 <= node < self.k + self.m:
                if node not in available_chunks:
                    return False
        return len(available_chunks) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> List[Tuple[int, int]]:
        """(offset, count) sub-chunk runs each helper must read."""
        y, x = lost_node // self.q, lost_node % self.q
        seq = self.q ** (self.t - 1 - y)
        out = []
        index = x * seq
        for _ in range(self.q ** y):
            out.append((index, seq))
            index += self.q * seq
        return out

    def get_repair_sub_chunk_count(self, want_to_read: Set[int]) -> int:
        weight = [0] * self.t
        for i in want_to_read:
            weight[i // self.q] += 1
        untouched = 1
        for y in range(self.t):
            untouched *= self.q - weight[y]
        return self.sub_chunk_no - untouched

    def minimum_to_decode(self, want_to_read: Set[int],
                          available_chunks: Set[int]
                          ) -> Dict[int, List[Tuple[int, int]]]:
        if self.is_repair(set(want_to_read), set(available_chunks)):
            return self._minimum_to_repair(set(want_to_read),
                                           set(available_chunks))
        ids = self._minimum_to_decode(set(want_to_read),
                                      set(available_chunks))
        return {i: [(0, self.sub_chunk_no)] for i in ids}

    def _minimum_to_repair(self, want_to_read: Set[int],
                           available_chunks: Set[int]
                           ) -> Dict[int, List[Tuple[int, int]]]:
        i = next(iter(want_to_read))
        lost = i if i < self.k else i + self.nu
        sub_ind = self.get_repair_subchunks(lost)
        minimum: Dict[int, List[Tuple[int, int]]] = {}
        for j in range(self.q):
            node = (lost // self.q) * self.q + j
            if j == lost % self.q:
                continue
            if node < self.k:
                minimum[node] = list(sub_ind)
            elif node >= self.k + self.nu:
                minimum[node - self.nu] = list(sub_ind)
        for chunk in sorted(available_chunks):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(chunk, list(sub_ind))
        assert len(minimum) == self.d
        return minimum

    def decode(self, want_to_read, chunks: Mapping[int, bytes],
               chunk_size: Optional[int] = None) -> Dict[int, bytes]:
        want = set(want_to_read)
        avail = set(chunks)
        if chunks and chunk_size and self.is_repair(want, avail) and \
                chunk_size > len(next(iter(chunks.values()))):
            return self._repair(want, chunks, chunk_size)
        return super().decode(want, chunks, chunk_size)

    def _repair(self, want_to_read: Set[int],
                chunks: Mapping[int, bytes],
                chunk_size: int) -> Dict[int, bytes]:
        """Bandwidth-optimal single-node repair from d partial helper
        reads (repair_one_lost_chunk)."""
        assert len(want_to_read) == 1 and len(chunks) == self.d
        q, t = self.q, self.t
        lost_i = next(iter(want_to_read))
        lost = lost_i if lost_i < self.k else lost_i + self.nu

        repair_subchunks = self.sub_chunk_no // q
        repair_blocksize = len(next(iter(chunks.values())))
        assert repair_blocksize % repair_subchunks == 0
        sc = repair_blocksize // repair_subchunks
        assert chunk_size == self.sub_chunk_no * sc

        sub_ind = self.get_repair_subchunks(lost)
        repair_planes = [z for (index, count) in sub_ind
                         for z in range(index, index + count)]
        plane_to_ind = {z: i for i, z in enumerate(repair_planes)}

        # helpers hold only the repair planes, (repair_subchunks, sc)
        helper: Dict[int, np.ndarray] = {}
        aloof: Set[int] = set()
        for i in range(self.k + self.m):
            node = i if i < self.k else i + self.nu
            if i in chunks:
                helper[node] = np.frombuffer(
                    chunks[i], dtype=np.uint8).reshape(
                        repair_subchunks, sc)
            elif i != lost_i:
                aloof.add(node)
        for i in range(self.k, self.k + self.nu):
            helper[i] = np.zeros((repair_subchunks, sc), dtype=np.uint8)
        assert len(helper) + len(aloof) + 1 == q * t

        recovered = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        U = {i: np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
             for i in range(q * t)}

        erasures = {lost - lost % q + i for i in range(q)} | aloof
        assert len(erasures) <= self.m + q - 1

        # order repair planes by intersection score across lost+aloof
        ordered: Dict[int, List[int]] = {}
        for z in repair_planes:
            z_vec = self._plane_vector(z)
            score = sum(1 for node in ({lost} | aloof)
                        if node % q == z_vec[node // q])
            assert score > 0
            ordered.setdefault(score, []).append(z)

        for score in sorted(ordered):
            for z in ordered[score]:
                z_vec = self._plane_vector(z)
                # fill uncoupled values for all non-erased nodes
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        node_sw = y * q + z_vec[y]
                        z_sw = self._z_sw(x, y, z, z_vec)
                        swapped = z_vec[y] > x
                        i0, i2 = (1, 3) if swapped else (0, 2)
                        i1, i3 = 1 - i0, 5 - i2
                        if node_sw in aloof:
                            out = self._pft_solve(
                                {i0: helper[node_xy][plane_to_ind[z]],
                                 i3: U[node_sw][z_sw]}, [i2])
                            U[node_xy][z] = out[i2]
                        elif z_vec[y] != x:
                            out = self._pft_solve(
                                {i0: helper[node_xy][plane_to_ind[z]],
                                 i1: helper[node_sw][plane_to_ind[z_sw]]},
                                [i2])
                            U[node_xy][z] = out[i2]
                        else:
                            U[node_xy][z] = helper[node_xy][plane_to_ind[z]]
                assert len(erasures) <= self.m
                self._decode_uncoupled(erasures, z, U)
                # recover coupled values of erased nodes on this plane
                for node in erasures:
                    x, y = node % q, node // q
                    node_sw = y * q + z_vec[y]
                    z_sw = self._z_sw(x, y, z, z_vec)
                    if node in aloof:
                        continue
                    if x == z_vec[y]:  # hole-dot pair
                        recovered[z] = U[node][z]
                    else:
                        assert y == lost // q and node_sw == lost
                        swapped = z_vec[y] > x
                        i0, i2 = (1, 3) if swapped else (0, 2)
                        i1 = 1 - i0
                        out = self._pft_solve(
                            {i0: helper[node][plane_to_ind[z]],
                             i2: U[node][z]}, [i1])
                        recovered[z_sw] = out[i1]

        recovered = np.ascontiguousarray(recovered)
        recovered.setflags(write=False)
        return {lost_i: recovered.reshape(-1).data}

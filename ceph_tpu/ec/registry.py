"""Erasure-code plugin registry.

Reference seam: ErasureCodePluginRegistry
(/root/reference/src/erasure-code/ErasureCodePlugin.h:45-79, .cc:86-196): a
singleton that dlopens `libec_<name>.so`, checks the plugin's version against
the build, calls its factory, and asserts the plugin echoes the profile back.

Here plugins are Python classes (optionally backed by native code or Pallas
kernels) registered by name.  Dynamic loading maps to `importlib` of
`ceph_tpu_ec_<name>` modules exposing `__erasure_code_init__(registry)` and
`__erasure_code_version__` — the same three-point contract (entry point,
version check, registration) so the reference's negative-path tests
(missing entry point, version mismatch, fail-to-register) carry over
(/root/reference/src/test/erasure-code/TestErasureCodePlugin.cc).
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, Optional

import ceph_tpu
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, ErasureCodeProfile

PLUGIN_VERSION = ceph_tpu.__version__
_MODULE_PREFIX = "ceph_tpu_ec_"  # the `libec_` analog for importable plugins

Factory = Callable[[ErasureCodeProfile], ErasureCode]


class ErasureCodePlugin:
    """A named factory with a version stamp."""

    def __init__(self, name: str, factory: Factory,
                 version: str = PLUGIN_VERSION):
        self.name = name
        self.factory = factory
        self.version = version


class ErasureCodePluginRegistry:
    _instance: Optional["ErasureCodePluginRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plugins: Dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
                _register_builtin(cls._instance)
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> int:
        with self._lock:
            if name in self._plugins:
                return -17  # EEXIST, same as the reference
            self._plugins[name] = plugin
            return 0

    def get(self, name: str) -> Optional[ErasureCodePlugin]:
        with self._lock:
            return self._plugins.get(name)

    def remove(self, name: str) -> int:
        with self._lock:
            return 0 if self._plugins.pop(name, None) else -2

    def load(self, name: str) -> ErasureCodePlugin:
        """Dynamic load of `ceph_tpu_ec_<name>` (dlopen analog).

        EXDEV on version mismatch, ENOENT on missing module, ENOEXEC on a
        module without the init entry point — the reference's error map
        (ErasureCodePlugin.cc:120-178).
        """
        plugin = self.get(name)
        if plugin is not None:
            return plugin
        try:
            mod = importlib.import_module(_MODULE_PREFIX + name)
        except ImportError as e:
            raise ErasureCodeError(2, f"load dlopen({name}): {e}")
        version = getattr(mod, "__erasure_code_version__", None)
        if version is None:
            raise ErasureCodeError(8, f"{name} has no version entry point")
        if version != PLUGIN_VERSION:
            raise ErasureCodeError(
                18, f"{name} version {version} != expected {PLUGIN_VERSION}")
        init = getattr(mod, "__erasure_code_init__", None)
        if init is None:
            raise ErasureCodeError(8, f"{name} has no init entry point")
        ret = init(self)
        if ret not in (0, None):
            raise ErasureCodeError(-ret if isinstance(ret, int) else 5,
                                   f"{name} init failed")
        plugin = self.get(name)
        if plugin is None:
            raise ErasureCodeError(6, f"{name} init did not register itself")
        return plugin

    def preload(self, plugins_csv: str) -> None:
        """Preload a comma-separated plugin list (osd_erasure_code_plugins;
        global_init_preload_erasure_code, global_init.cc:587-620)."""
        for name in filter(None, (p.strip() for p in plugins_csv.split(","))):
            self.load(name)

    def factory(self, plugin_name: str, profile: ErasureCodeProfile,
                ) -> ErasureCode:
        plugin = self.get(plugin_name) or self.load(plugin_name)
        codec = plugin.factory(profile)
        # The reference asserts the codec echoes the profile back
        # (ErasureCodePlugin.cc:104-112).
        prof = codec.get_profile()
        for key, val in profile.items():
            assert prof.get(key) == val, f"plugin dropped profile key {key}"
        return codec

    def names(self):
        with self._lock:
            return sorted(self._plugins)


def _make_jax_factory(technique: str) -> Factory:
    def factory(profile: ErasureCodeProfile) -> ErasureCode:
        from ceph_tpu.ec.bitmatrix_plugin import ErasureCodeJaxBitmatrix
        from ceph_tpu.ec.jax_plugin import ErasureCodeJax

        tech = profile.get("technique", technique)
        if tech in ErasureCodeJaxBitmatrix.TECHNIQUES:
            codec: ErasureCode = ErasureCodeJaxBitmatrix(technique=tech)
        else:
            codec = ErasureCodeJax(technique=tech)
        codec.init(profile)
        return codec

    return factory


def _register_builtin(reg: ErasureCodePluginRegistry) -> None:
    # `ec_jax` is the flagship plugin; `jerasure` and `isa` are registered as
    # compatibility aliases so reference profiles
    # (plugin=jerasure technique=reed_sol_van k=2 m=2 — the
    # osd_pool_default_erasure_code_profile) resolve to the TPU codec.
    for name in ("ec_jax", "jerasure", "isa"):
        reg.add(name, ErasureCodePlugin(name, _make_jax_factory("reed_sol_van")))

    def lrc_factory(profile: ErasureCodeProfile) -> ErasureCode:
        from ceph_tpu.ec.lrc import ErasureCodeLrc

        codec = ErasureCodeLrc()
        codec.init(profile)
        return codec

    def shec_factory(profile: ErasureCodeProfile) -> ErasureCode:
        from ceph_tpu.ec.shec import ErasureCodeShec

        codec = ErasureCodeShec(
            technique=profile.setdefault("technique", "multiple"))
        codec.init(profile)
        return codec

    def clay_factory(profile: ErasureCodeProfile) -> ErasureCode:
        from ceph_tpu.ec.clay import ErasureCodeClay

        codec = ErasureCodeClay()
        codec.init(profile)
        return codec

    def msr_factory(profile: ErasureCodeProfile) -> ErasureCode:
        from ceph_tpu.ec.msr import ErasureCodeMsr

        codec = ErasureCodeMsr()
        codec.init(profile)
        return codec

    reg.add("lrc", ErasureCodePlugin("lrc", lrc_factory))
    reg.add("shec", ErasureCodePlugin("shec", shec_factory))
    reg.add("clay", ErasureCodePlugin("clay", clay_factory))
    reg.add("ec_msr", ErasureCodePlugin("ec_msr", msr_factory))


def create_erasure_code(profile: ErasureCodeProfile) -> ErasureCode:
    """Build a codec from a reference-style profile string map."""
    plugin = profile.get("plugin", "ec_jax")
    return ErasureCodePluginRegistry.instance().factory(plugin, dict(profile))

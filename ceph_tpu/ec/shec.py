"""SHEC — shingled erasure code (k, m, c).

Reference parity: the shec plugin
(/root/reference/src/erasure-code/shec/ErasureCodeShec.{h,cc}):

- generator: start from the jerasure Vandermonde RS coding matrix and zero
  a sliding window of columns per parity row so each parity "shingle"
  covers only part of the data (shec_reedsolomon_coding_matrix :461-529);
  technique=multiple searches (m1,c1)/(m2,c2) splits minimizing the
  recovery-efficiency metric (shec_calc_recovery_efficiency1), single uses
  one band;
- decode: per erasure pattern, search parity subsets (fewest parities
  first) for an invertible recovery submatrix
  (shec_make_decoding_matrix :531-696), cache the result keyed by the
  (want, avails) signature (ErasureCodeShecTableCache);
- validation: 0 < c <= m <= k <= 12, k+m <= 20, w in {8,16,32}
  (ErasureCodeShecReedSolomonVandermonde::parse :276-380).

TPU-first: the recovery search and inversion are host-side (tiny
matrices); the bulk encode/decode matmuls run through the same
bit-decomposed GF(2^8) MXU kernel as ec_jax.  This build fixes w=8 (the
default); GF(2^16/32) shingles are not provided.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Set, Tuple

import numpy as np

from ceph_tpu.ec import dispatch
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, to_int
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import gf


def recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """shec_calc_recovery_efficiency1: mean chunks read to recover."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [10 ** 8] * k
    r_e1 = 0.0
    for m_band, c_band, _row0 in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(m_band):
            start = ((rr * k) // m_band) % k
            end = (((rr + c_band) * k) // m_band) % k
            width = ((rr + c_band) * k) // m_band - (rr * k) // m_band
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], width)
                cc = (cc + 1) % k
            r_e1 += width
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_matrix(k: int, m: int, c: int, technique: str) -> np.ndarray:
    """The shingled generator rows (m, k) over GF(2^8)."""
    if technique == "single":
        m1, c1 = 0, 0
    else:
        best = None
        for c1_try in range(c // 2 + 1):
            for m1_try in range(m + 1):
                c2 = c - c1_try
                m2 = m - m1_try
                if m1_try < c1_try or m2 < c2:
                    continue
                if (m1_try == 0) != (c1_try == 0):
                    continue
                if (m2 == 0) != (c2 == 0):
                    continue
                r = recovery_efficiency1(k, m1_try, m2, c1_try, c2)
                if r < 0:
                    continue
                if best is None or r < best[0] - 1e-12:
                    best = (r, m1_try, c1_try)
        if best is None:
            raise ErasureCodeError(22, f"no valid shec split for"
                                   f" k={k} m={m} c={c}")
        _, m1, c1 = best
    m2, c2 = m - m1, c - c1

    matrix = rs.reed_sol_van_matrix(k, m).copy()
    for band_m, band_c, row0 in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(band_m):
            end = ((rr * k) // band_m) % k
            start = (((rr + band_c) * k) // band_m) % k
            cc = start
            while cc != end:
                matrix[row0 + rr, cc] = 0
                cc = (cc + 1) % k
    return matrix


class ErasureCodeShec(ErasureCode):
    TECHNIQUES = ("single", "multiple")
    DEFAULT_K, DEFAULT_M, DEFAULT_C, DEFAULT_W = 4, 3, 2, 8

    def __init__(self, technique: str = "multiple") -> None:
        super().__init__()
        if technique not in self.TECHNIQUES:
            raise ErasureCodeError(
                22, f"technique={technique} is not a valid coding technique")
        self.technique = technique
        self.c = 0
        self.w = 8
        self.matrix: Optional[np.ndarray] = None
        self._mbits_dev = None
        self.use_tpu = True
        self._decode_cache = dispatch.LruCache(256)

    # -- init -------------------------------------------------------------

    def init(self, profile: Dict[str, str]) -> None:
        profile["technique"] = self.technique
        has = [name for name in ("k", "m", "c") if profile.get(name)]
        if not has:
            self.k, self.m, self.c = (
                self.DEFAULT_K, self.DEFAULT_M, self.DEFAULT_C)
            profile.update(
                {"k": str(self.k), "m": str(self.m), "c": str(self.c)})
        elif len(has) != 3:
            raise ErasureCodeError(22, "(k, m, c) must all be chosen")
        else:
            self.k = to_int("k", profile, str(self.DEFAULT_K))
            self.m = to_int("m", profile, str(self.DEFAULT_M))
            self.c = to_int("c", profile, str(self.DEFAULT_C))
        k, m, c = self.k, self.m, self.c
        if k <= 0 or m <= 0 or c <= 0:
            raise ErasureCodeError(22, "k, m, c must be positive")
        if m < c:
            raise ErasureCodeError(22, f"c={c} must be <= m={m}")
        if k > 12:
            raise ErasureCodeError(22, f"k={k} must be <= 12")
        if k + m > 20:
            raise ErasureCodeError(22, f"k+m={k + m} must be <= 20")
        if k < m:
            raise ErasureCodeError(22, f"m={m} must be <= k={k}")
        self.w = to_int("w", profile, str(self.DEFAULT_W))
        if self.w != 8:
            # the reference silently falls back to 8 on bad w; GF(2^16/32)
            # shingles are out of scope for the TPU build
            self.w = 8
            profile["w"] = "8"
        from ceph_tpu.ec.interface import to_bool

        self.use_tpu = to_bool("tpu", profile, "true") and \
            gf.backend_available()
        super().init(profile)
        self.matrix = shec_matrix(k, m, c, self.technique)

    # -- geometry ---------------------------------------------------------

    def get_alignment(self) -> int:
        return self.k * self.w * 4

    # -- kernels ----------------------------------------------------------

    def _matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        return dispatch.gf_matmul(mat, data, self.use_tpu)

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        # in-place reads + buffer-view writes: the bytes()/tobytes()
        # round trip copied every chunk twice more per encode
        data = np.stack([
            np.frombuffer(encoded[i], dtype=np.uint8)
            for i in range(k)])
        parity = np.ascontiguousarray(self._matmul(self.matrix, data))
        for j in range(m):
            encoded[k + j][:] = parity[j].data

    # -- recovery-set search (shec_make_decoding_matrix) ------------------

    def _search_recovery(self, want: Tuple[int, ...],
                         avails: Tuple[int, ...]):
        """-> (rows, cols, inv_matrix, minimum) for an erasure signature.

        rows: chunk ids feeding the solve; cols: data ids recovered;
        inv: (len, len) GF inverse mapping chunk values -> data values;
        minimum: chunk ids to read (reference `minimum` array semantics).
        """
        return self._decode_cache.get_or_compute(
            (want, avails), lambda: self._search_recovery_uncached(want, avails))

    def _search_recovery_uncached(self, want: Tuple[int, ...],
                                  avails: Tuple[int, ...]):
        k, m = self.k, self.m
        want_arr = list(want)
        # a wanted missing parity forces wanting its whole data window
        for i in range(m):
            if want_arr[k + i] and not avails[k + i]:
                for j in range(k):
                    if self.matrix[i, j]:
                        want_arr[j] = 1

        best = None  # (dup, ek, rows, cols, inv)
        for pp in range(1 << m):
            parities = [i for i in range(m) if pp & (1 << i)]
            ek = len(parities)
            if best is not None and ek > best[1]:
                continue
            if any(not avails[k + p] for p in parities):
                continue
            rows = set()
            cols = set()
            for i in range(k):
                if want_arr[i] and not avails[i]:
                    cols.add(i)
            for p in parities:
                rows.add(k + p)
                for j in range(k):
                    if self.matrix[p, j]:
                        cols.add(j)
                        if avails[j]:
                            rows.add(j)
            if len(rows) != len(cols):
                continue
            dup = len(rows)
            if dup == 0:
                best = (0, ek, [], [], None)
                break
            if best is not None and dup >= best[0]:
                continue
            row_ids = sorted(rows)
            col_ids = sorted(cols)
            sub = np.zeros((dup, dup), dtype=np.uint8)
            for ri, r in enumerate(row_ids):
                for ci, col in enumerate(col_ids):
                    if r < k:
                        sub[ri, ci] = 1 if r == col else 0
                    else:
                        sub[ri, ci] = self.matrix[r - k, col]
            try:
                inv = gf.gf_invert_matrix(sub)
            except Exception:
                continue  # singular: this parity subset can't recover
            best = (dup, ek, row_ids, col_ids, inv)

        if best is None:
            result = None
        else:
            dup, ek, row_ids, col_ids, inv = best
            minimum = set(row_ids)
            for i in range(k):
                if want_arr[i] and avails[i]:
                    minimum.add(i)
            for i in range(m):
                if want[k + i] and avails[k + i] and (k + i) not in minimum:
                    # an available wanted parity still has to be read unless
                    # it is re-computable purely from wanted data
                    if any(self.matrix[i, j] and not want_arr[j]
                           for j in range(k)):
                        minimum.add(k + i)
            result = (row_ids, col_ids, inv, sorted(minimum))
        return result

    def _signature(self, want_to_read: Set[int], available: Set[int]):
        n = self.k + self.m
        want = tuple(1 if i in want_to_read else 0 for i in range(n))
        avails = tuple(1 if i in available else 0 for i in range(n))
        return want, avails

    # -- decode planning --------------------------------------------------

    def _minimum_to_decode(self, want_to_read: Set[int],
                           available_chunks: Set[int]) -> Set[int]:
        if not want_to_read:
            return set()
        if want_to_read <= available_chunks:
            return set(want_to_read)
        want, avails = self._signature(want_to_read, available_chunks)
        result = self._search_recovery(want, avails)
        if result is None:
            raise ErasureCodeError(
                5, "can't find recover matrix for erasure pattern")
        return set(result[3])

    # -- decode -----------------------------------------------------------

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        available = set(chunks)
        want, avails = self._signature(set(want_to_read), available)
        result = self._search_recovery(want, avails)
        if result is None:
            raise ErasureCodeError(
                5, "can't find recover matrix for erasure pattern")
        row_ids, col_ids, inv, _minimum = result
        if row_ids:
            # np.stack owns the copy it needs at read time; recovered
            # columns land back as buffer views (writes target erased
            # buffers only — disjoint from the stacked sources)
            src = np.stack([
                np.frombuffer(decoded[r], dtype=np.uint8)
                for r in row_ids])
            out = np.ascontiguousarray(self._matmul(inv, src))
            for ci, col in enumerate(col_ids):
                decoded[col][:] = out[ci].data
        # wanted missing parity: re-encode from (now complete) data windows
        lost_parity = [i for i in range(m)
                       if (k + i) in want_to_read and (k + i) not in available]
        if lost_parity:
            data = np.stack([
                np.frombuffer(decoded[i], dtype=np.uint8)
                for i in range(k)])
            parity = np.ascontiguousarray(
                self._matmul(self.matrix[lost_parity, :], data))
            for row, i in enumerate(lost_parity):
                decoded[k + i][:] = parity[row].data

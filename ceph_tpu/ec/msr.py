"""`ec_msr` — product-matrix MSR regenerating codec (repair-bandwidth optimal).

Construction: the product-matrix MSR code of Rashmi, Shah & Kumar at the
d = 2k-2 point (arXiv:1412.3022 runs the same family on accelerators; the
original construction is arXiv:1005.4178 §V).  Each chunk holds alpha =
d-k+1 sub-chunks; single-chunk repair reads only beta = chunk/alpha bytes
from each of d helpers instead of k full chunks — total repair traffic
d/(k*alpha) of the object vs the classic 1.0.

Shape of the math, all GF(2^8) linear algebra:

- message matrix M = [S1; S2] with S1, S2 symmetric alpha x alpha;
- encoding matrix Psi with rows psi_i = (1, x_i, ..., x_i^(2*alpha-1))
  (Vandermonde — so Psi = [Phi, Lambda*Phi] with Phi the first alpha
  columns and lambda_i = x_i^alpha), x_i distinct AND x_i^alpha distinct;
- node i stores psi_i @ M (alpha symbols);
- repair of node f: helper i ships the scalar stream
  (stored_i) @ phi_f^T; d of those invert to M @ phi_f^T and the lost
  chunk is S1@phi_f^T + lambda_f * S2@phi_f^T by symmetry.

d > 2k-2 is reached by SHORTENING: run the (n+x, k+x, d+x) auxiliary code
with x = d-2k+2 phantom all-zero systematic nodes.  Phantoms store zeros
(asserted at init), so their helper contributions are known without any
I/O and every real repair still needs exactly d real helpers.  d < 2k-2
has no product-matrix construction; those profiles degrade to a plain
Reed-Solomon layout (alpha = 1) where repair IS k-read decode — the codec
still round-trips, it just reports supports_fractional_repair() False.

The product-matrix code is not systematic natively; a linear remapping
(precomputed at init: solve the k*alpha systematic constraints for the
free symbols) turns it into one, so reads of healthy data chunks stay
zero-decode like every other codec here.

Device routing: encode/decode ride the shared dispatch.gf_matmul seam
(plan kinds encode/matmul); repair projections and reconstructions ride
the dedicated `repair` plan kind (dispatch.gf_repair_matmul — matrix
baked into the trace, memoized by codec signature + erasure pattern,
xsched-compiled when the bit expansion wins, `ec-repair` breaker family,
bit-exact numpy host fallback).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from ceph_tpu.ec import dispatch
from ceph_tpu.ec.interface import (SIMD_ALIGN, ErasureCode, ErasureCodeError,
                                   to_bool, to_int)
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import gf


def _gf_pow_vec(base: np.ndarray, n: int) -> np.ndarray:
    out = np.ones_like(base)
    for _ in range(n):
        out = gf.gf_mul(out, base)
    return out


class ErasureCodeMsr(ErasureCode):
    """Product-matrix MSR codec with fractional single-chunk repair."""

    technique = "product_matrix_msr"

    def __init__(self) -> None:
        super().__init__()
        self.w = 8
        self.d = 0
        self.alpha = 1
        self.sub_chunk_bytes = 0
        self._pm = False           # product-matrix mode (vs RS fallback)
        self._x = 0                # shortening: phantom systematic nodes
        self._psi: Optional[np.ndarray] = None   # (n+x, 2*alpha)
        self._phi: Optional[np.ndarray] = None   # (n+x, alpha)
        self._lam: Optional[np.ndarray] = None   # (n+x,)
        self.gen: Optional[np.ndarray] = None    # (n*alpha, k*alpha)
        self.parity_mat: Optional[np.ndarray] = None  # (m*alpha, k*alpha)
        self.use_tpu = True
        self.tpu_min_bytes = 1
        self.use_plan = True
        self._plan_sig: Optional[str] = None

    # -- init -------------------------------------------------------------

    def init(self, profile: Dict[str, str]) -> None:
        profile["technique"] = self.technique
        self.k = to_int("k", profile, "4")
        self.m = to_int("m", profile, "3")
        self.w = to_int("w", profile, "8")
        if self.w != 8:
            raise ErasureCodeError(22, "ec_msr supports w=8 only")
        self.sanity_check_k_m(self.k, self.m)
        n = self.k + self.m
        # d defaults to all surviving chunks — the most repair-frugal
        # point of the family (beta shrinks as d grows)
        self.d = to_int("d", profile, str(n - 1))
        if not self.k <= self.d <= n - 1:
            raise ErasureCodeError(
                22, f"d={self.d} must satisfy k <= d <= k+m-1")
        self.use_tpu = to_bool("tpu", profile, "true") and \
            gf.backend_available()
        self.tpu_min_bytes = to_int("tpu-min-bytes", profile, "1")
        self.use_plan = to_bool("plan-cache", profile, "true")
        super().init(profile)
        self._prepare()

    def _prepare(self) -> None:
        self._x = self.d - 2 * self.k + 2
        self._pm = self._x >= 0
        if not self._pm:
            # no product-matrix point below d = 2k-2: plain RS layout,
            # repair degenerates to k-read decode (alpha stays 1)
            self.alpha = 1
            parity = rs.reed_sol_van_matrix(self.k, self.m)
            self.gen = np.vstack([
                np.eye(self.k, dtype=np.uint8), parity])
            self.parity_mat = np.ascontiguousarray(parity)
            return
        self.alpha = self.d - self.k + 1
        self._build_product_matrix()

    def _build_product_matrix(self) -> None:
        k, n, alpha, x = self.k, self.k + self.m, self.alpha, self._x
        n_aux = n + x                  # auxiliary code is (n+x, k+x, d+x)
        k_aux = k + x
        d_aux = 2 * alpha              # = d + x = 2*k_aux - 2
        xs: List[int] = []
        lams_seen: Set[int] = set()
        # greedy point selection: x_i distinct nonzero with x_i^alpha
        # distinct too (Psi any-d'-rows and the repair/reconstruction
        # theorems need both); c -> c^alpha has 255/gcd(alpha,255)
        # distinct images, so small alpha never runs dry for sane n
        for c in range(1, 256):
            lam = gf.gf_pow(c, alpha)
            if lam in lams_seen:
                continue
            lams_seen.add(lam)
            xs.append(c)
            if len(xs) == n_aux:
                break
        if len(xs) < n_aux:
            raise ErasureCodeError(
                22, f"k={k} m={self.m} d={self.d}: GF(256) has too few "
                f"product-matrix points for alpha={alpha}")
        pts = np.array(xs, dtype=np.uint8)
        self._psi = np.stack(
            [_gf_pow_vec(pts, j) for j in range(d_aux)], axis=1)
        self._phi = self._psi[:, :alpha]
        self._lam = _gf_pow_vec(pts, alpha)

        # systematic remapping: solve the k_aux*alpha constraints
        # "aux node i stores its own data" for the free symbols of
        # [S1; S2], then drop the phantom (all-zero) data columns
        node_rows = np.vstack(
            [self._aux_node_rows(i) for i in range(n_aux)])
        constraints = node_rows[:k_aux * alpha]
        try:
            inv = gf.gf_invert_matrix(constraints)
        except Exception as e:  # pragma: no cover - construction bug guard
            raise ErasureCodeError(
                22, f"ec_msr constraint matrix singular: {e}")
        gen_aux = gf.gf_matmul_ref(node_rows, inv[:, x * alpha:])
        # phantoms must store zeros (their repair contribution is the
        # known-zero stream) and real data nodes must be systematic
        assert not gen_aux[:x * alpha].any(), "phantom rows not zero"
        assert np.array_equal(
            gen_aux[x * alpha:k_aux * alpha],
            np.eye(k * alpha, dtype=np.uint8)), "systematic block broken"
        self.gen = np.ascontiguousarray(gen_aux[x * alpha:])
        self.parity_mat = np.ascontiguousarray(self.gen[k * alpha:])

    def _aux_node_rows(self, i: int) -> np.ndarray:
        """(alpha, alpha*(alpha+1)) coefficients of aux node i's stored
        symbols over the free symbols of [S1; S2] (upper-triangle
        order, S1 block then S2 block): stored_i = phi_i@S1 +
        lambda_i*phi_i@S2 with S1/S2 symmetric."""
        alpha = self.alpha
        phi = self._phi[i]
        lam = int(self._lam[i])
        rows = np.zeros((alpha, alpha * (alpha + 1)), dtype=np.uint8)
        t = 0
        for scale in (1, lam):
            for p in range(alpha):
                for q in range(p, alpha):
                    if p == q:
                        rows[p, t] ^= gf.gf_mul(int(phi[p]), scale)
                    else:
                        rows[q, t] ^= gf.gf_mul(int(phi[p]), scale)
                        rows[p, t] ^= gf.gf_mul(int(phi[q]), scale)
                    t += 1
        return rows

    # -- geometry ---------------------------------------------------------

    def get_alignment(self) -> int:
        # chunk must split into alpha equal sub-chunks, each lane-wide
        return self.k * self.alpha * SIMD_ALIGN

    def get_sub_chunk_count(self) -> int:
        return self.alpha

    # -- capability surface ------------------------------------------------

    def supports_fractional_repair(self) -> bool:
        return self._pm and self.alpha > 1

    def repair_degree(self) -> int:
        return self.d

    def minimum_to_repair(self, lost: int, available: Set[int],
                          prefer: Optional[Sequence[int]] = None
                          ) -> Dict[int, List[tuple]]:
        """The d helpers (and the 1-of-alpha sub-chunk fraction each
        ships) for single-chunk repair — the fractional twin of
        minimum_to_decode.  `prefer` ranks the helper pool (the
        daemon passes its EWMA shard ranking)."""
        if not self.supports_fractional_repair():
            raise ErasureCodeError(95, "codec has no fractional repair")
        pool = [c for c in available if c != lost]
        if len(pool) < self.d:
            raise ErasureCodeError(
                5, f"need {self.d} helpers, have {len(pool)}")
        if prefer is not None:
            order = {c: i for i, c in enumerate(prefer)}
            pool.sort(key=lambda c: (order.get(c, len(order)), c))
        else:
            pool.sort()
        return {h: [(0, 1)] for h in pool[:self.d]}

    # -- kernels ----------------------------------------------------------

    def plan_signature(self) -> str:
        if self._plan_sig is None:
            from ceph_tpu.ec import plan

            self._plan_sig = plan.codec_signature(
                f"{self.technique}_d{self.d}", self.k, self.m, self.w,
                self.gen)
        return self._plan_sig

    def _matmul(self, mat: np.ndarray, data: np.ndarray,
                encode: bool) -> np.ndarray:
        sig = self.plan_signature() if encode else None
        return dispatch.gf_matmul(
            mat, data, self.use_tpu, self.tpu_min_bytes, sig=sig,
            use_plan=self.use_plan,
            family="ec-encode" if encode else "ec-decode")

    def _repair_matmul(self, mat: np.ndarray, data: np.ndarray,
                       sig_extra: str) -> np.ndarray:
        return dispatch.gf_repair_matmul(
            mat, data, self.use_tpu, self.tpu_min_bytes,
            sig=f"{self.plan_signature()}/{sig_extra}",
            use_plan=self.use_plan)

    def _to_syms(self, data: np.ndarray) -> np.ndarray:
        """(..., R, C) chunks -> (..., R*alpha, C/alpha) sub-chunk
        symbol rows (sub-chunk a of chunk r is row r*alpha+a).

        Sub-chunks are byte-INTERLEAVED (symbol a holds the chunk
        bytes at positions == a mod alpha), not contiguous blocks:
        the interleave is invariant under concatenation and under any
        alpha-aligned slice, so the per-stripe interface path, the
        whole-stream batched path (ec_util feeds shard STREAMS as one
        batch column), and ranged chunk reads all see the same
        layout — chunk sizes are alpha-aligned by get_alignment."""
        c = data.shape[-1]
        if c % self.alpha:
            raise ErasureCodeError(
                22, f"chunk size {c} not divisible by alpha={self.alpha}")
        sc = c // self.alpha
        arr = np.moveaxis(
            data.reshape(data.shape[:-1] + (sc, self.alpha)), -1, -2)
        return np.ascontiguousarray(arr).reshape(
            data.shape[:-2] + (data.shape[-2] * self.alpha, sc))

    def _from_syms(self, syms: np.ndarray, rows: int) -> np.ndarray:
        sc = syms.shape[-1]
        lead = syms.shape[:-2]
        arr = np.moveaxis(
            np.asarray(syms).reshape(lead + (rows, self.alpha, sc)),
            -1, -2)
        return np.ascontiguousarray(arr).reshape(
            lead + (rows, self.alpha * sc))

    # -- encode / decode --------------------------------------------------

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        data = np.stack([
            np.frombuffer(encoded[self.chunk_index(i)], dtype=np.uint8)
            for i in range(k)])
        syms = self._to_syms(data)
        parity = self._from_syms(
            np.ascontiguousarray(
                self._matmul(self.parity_mat, syms, encode=True)), m)
        for j in range(m):
            encoded[self.chunk_index(k + j)][:] = parity[j].data

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        erasures = [i for i in range(k + m)
                    if self.chunk_index(i) not in chunks]
        if not erasures:
            return
        have = [i for i in range(k + m)
                if self.chunk_index(i) in chunks][:k]
        if len(have) < k:
            raise ErasureCodeError(5, "not enough chunks to decode")
        dmat = self._decode_matrix(tuple(have), tuple(erasures))
        src = self._to_syms(np.stack([
            np.frombuffer(decoded[self.chunk_index(i)], dtype=np.uint8)
            for i in have]))
        out = self._from_syms(
            np.ascontiguousarray(self._matmul(dmat, src, encode=False)),
            len(erasures))
        for row, e in enumerate(erasures):
            decoded[self.chunk_index(e)][:] = out[row].data

    def _decode_matrix(self, have: tuple, erasures: tuple) -> np.ndarray:
        """(len(erasures)*alpha, k*alpha) rows mapping survivor symbols
        straight to erased symbols, shared across codec instances."""
        alpha = self.alpha

        def compute() -> np.ndarray:
            surv = np.vstack([
                self.gen[s * alpha:(s + 1) * alpha] for s in have])
            try:
                inv = gf.gf_invert_matrix(surv)
            except Exception:
                raise ErasureCodeError(5, "survivor matrix singular")
            lost = np.vstack([
                self.gen[e * alpha:(e + 1) * alpha] for e in erasures])
            return np.ascontiguousarray(gf.gf_matmul_ref(lost, inv))

        return dispatch.shared_decode_rows(
            (self.plan_signature(), "dec", tuple(have), tuple(erasures)),
            compute)

    # -- batched API -------------------------------------------------------

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(B, k, C) uint8 stripes -> (B, m, C) parity, one dispatch."""
        assert data.ndim == 3 and data.shape[1] == self.k
        return self._from_syms(
            self._matmul(self.parity_mat, self._to_syms(data),
                         encode=True), self.m)

    def decode_batch(self, have: tuple, erasures: tuple,
                     survivors: np.ndarray) -> np.ndarray:
        """(B, k, C) surviving chunks (rows in `have` order) -> erased."""
        dmat = self._decode_matrix(tuple(have), tuple(erasures))
        return self._from_syms(
            self._matmul(dmat, self._to_syms(survivors), encode=False),
            len(erasures))

    # -- fractional repair -------------------------------------------------

    def repair_vector(self, lost: int) -> np.ndarray:
        """(alpha,) projection vector phi_f every helper applies to its
        own stored sub-chunks — identical across helpers."""
        if not self.supports_fractional_repair():
            raise ErasureCodeError(95, "codec has no fractional repair")
        if not 0 <= lost < self.k + self.m:
            raise ErasureCodeError(22, f"bad chunk id {lost}")
        return np.ascontiguousarray(self._phi[self._x + lost])

    def repair_project(self, lost: int, chunk) -> bytes:
        """Helper-side projection: a stored shard stream -> its beta =
        len/alpha byte repair fragment, one (1 x alpha) GF matmul.
        The byte-interleaved sub-chunk layout makes this independent
        of how many stripes the stream concatenates (fragment byte j
        covers stream bytes j*alpha..j*alpha+alpha-1), so helpers can
        project whole shard streams without knowing the stripe
        geometry."""
        data = np.frombuffer(chunk, dtype=np.uint8)
        syms = self._to_syms(data.reshape(1, 1, -1))  # (1, alpha, sc)
        vec = self.repair_vector(lost)[None, :]
        out = self._repair_matmul(vec, syms, sig_extra=f"proj{lost}")
        # beta-byte wire fragment: the matmul result must materialize
        # once at the array -> bytes boundary (it is 1/alpha of the
        # shard, the bandwidth win, not a redundant copy)
        return np.ascontiguousarray(out).tobytes()  # lint: disable=hot-path-copy

    def repair_matrix(self, lost: int,
                      helpers: Tuple[int, ...]) -> np.ndarray:
        """(alpha, d) reconstruction matrix mapping the d helper
        fragments (rows in `helpers` order) to the lost chunk's
        sub-chunks, cached per (codec, erasure pattern)."""
        if not self.supports_fractional_repair():
            raise ErasureCodeError(95, "codec has no fractional repair")
        helpers = tuple(helpers)
        if len(set(helpers)) != self.d or lost in helpers or \
                not all(0 <= h < self.k + self.m for h in helpers):
            raise ErasureCodeError(
                22, f"repair of {lost} needs {self.d} distinct helpers")

        def compute() -> np.ndarray:
            x, alpha = self._x, self.alpha
            lam_f = int(self._lam[x + lost])
            # phantom contributions are the zero stream, so only their
            # psi rows join the inversion; their columns of the result
            # multiply zeros and are dropped
            rows = list(range(x)) + [x + h for h in helpers]
            psi_sub = self._psi[rows]
            try:
                inv = gf.gf_invert_matrix(psi_sub)
            except Exception:
                raise ErasureCodeError(5, "helper matrix singular")
            # stored_f = S1@phi_f + lambda_f * S2@phi_f; inv's top/bot
            # halves give S1@phi_f and S2@phi_f from the contributions
            combine = np.hstack([
                np.eye(alpha, dtype=np.uint8),
                gf.gf_mul(np.eye(alpha, dtype=np.uint8),
                          np.uint8(lam_f))])
            full = gf.gf_matmul_ref(combine, inv)   # (alpha, d+x)
            return np.ascontiguousarray(full[:, x:])

        return dispatch.shared_decode_rows(
            (self.plan_signature(), "rep", int(lost), helpers), compute)

    def repair_syms(self, lost: int, helpers: Tuple[int, ...],
                    fragments: np.ndarray) -> np.ndarray:
        """(d, S) stacked helper fragments (rows in `helpers` order,
        streams from many objects may be concatenated along S) ->
        (alpha, S) lost sub-chunk rows in one plan-cached dispatch."""
        rmat = self.repair_matrix(lost, helpers)
        hsig = "h" + "_".join(str(h) for h in helpers)
        return np.ascontiguousarray(self._repair_matmul(
            rmat, np.ascontiguousarray(fragments),
            sig_extra=f"rep{lost}/{hsig}"))

    def repair_assemble(self, syms: np.ndarray) -> bytes:
        """(alpha, S) repaired sub-chunk rows -> the lost shard stream
        (byte j*alpha + a is row a, column j — the _to_syms byte
        interleave, valid for any stripe count)."""
        # the de-interleave transpose is a gather: contiguous output
        # bytes cannot be a view of the (alpha, S) row layout
        return np.ascontiguousarray(np.asarray(syms).T).tobytes()  # lint: disable=hot-path-copy

    def repair(self, lost: int, fragments: Mapping[int, bytes]) -> bytes:
        """Primary-side reconstruction: {helper chunk id: beta-byte
        fragment} -> the lost shard stream, bit-exact vs full decode."""
        helpers = tuple(sorted(fragments))
        sizes = {len(fragments[h]) for h in helpers}
        if len(sizes) != 1:
            raise ErasureCodeError(22, "ragged helper fragments")
        frag = np.stack([
            np.frombuffer(fragments[h], dtype=np.uint8) for h in helpers])
        out = self.repair_syms(lost, helpers, frag)
        return self.repair_assemble(out)

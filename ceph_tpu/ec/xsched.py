"""Plan-time XOR-schedule compiler for the GF(2) bitmatrix family.

The XOR-EC program-optimization literature (arXiv:2108.02692) treats
an erasure code's bit matrix as a PROGRAM, not an operand: every
output row is an XOR of input columns, shared sub-XORs can be
computed once (common-subexpression elimination), the resulting ops
can be scheduled for temporary locality, and the whole compiled
artifact memoized — cutting the XOR count 30-60% before a single
byte moves.  This module is that compiler:

* **CSE pass** — greedy pairwise extraction (Paar's algorithm):
  repeatedly find the column pair shared by the most output rows,
  hoist it into a temporary, and substitute.  Each extraction with
  multiplicity c saves c-1 XOR region ops.
* **Scheduling pass** — temporaries are emitted in dependency-DFS
  order from the outputs (producers land next to their consumers),
  then a linear-scan allocator maps them onto a bounded set of
  reusable buffer slots: `n_slots` — the live-temporary bound — is
  what the executor must allocate, not the temp count.
* **Memoization** — compiled schedules are cached in a bounded LRU
  keyed by the same sha256 matrix/codec signature the ExecPlan cache
  uses (`matrix_signature` lives HERE and ec/plan.py re-exports it);
  decode schedules key per erasure-pattern submatrix content, so a
  re-instantiated codec or a rebuilt plan (mesh shrink, quarantine)
  never recompiles a known matrix.

Three executors lower a schedule:

* the NATIVE tier (`lower_program` + `execute_native`) flattens a
  schedule ONCE into an `XorProgram` — a flat int32 op tape of
  ``(dst, srcA, srcB)`` region triples over a uniform region arena
  ``(n_objects, n_regions, region_bytes)`` — memoized next to the
  schedule in the same signature cache, and runs the whole tape in a
  single C++ call (native/src/xor_sched.cc: word-wide uint64 XOR
  loops, unrolled).  This is the small-op band winner: one
  Python→native transition per BATCH instead of one numpy dispatch
  per XOR, and the same tape replays over N packed objects;
* the HOST tier (`execute_host`) runs the program over numpy buffer
  views — the bitmatrix trio's packet regions (models/bitmatrix
  `packet_views`) execute in place with zero stacking/transpose
  copies; and
* the DEVICE tier lives in ec/plan.py as the `xor_sched` plan kind
  (the same program over bit planes, traced next to the
  `_gf2_matmul_bytes_impl` matmul lowering) — consumers pick
  schedule-vs-matmul by the measured op count (`prefer_schedule`).

`execute()` is the tier seam: native when built and enabled, host
fallback always available.

Kill switches: CEPH_TPU_XSCHED=0 pins every caller to the naive
row-walk (`naive_xor_matmul`, bit-identical output);
CEPH_TPU_NATIVE_XSCHED=0 pins schedule execution to the host tier
(native and host are bit-identical too — the parity sweep in
tests/test_xsched_native.py holds all three equal byte-for-byte).
Stats land in `plan.stats()["xsched"]` — schedules compiled, cache
hits, xors_naive vs xors_scheduled, native-vs-host executions and
tape-cache hits/misses.

This module must stay importable without jax (the host tier is pure
numpy) and must not import ec/plan.py (plan imports us).
"""

from __future__ import annotations

import ctypes
import hashlib
import os

from ceph_tpu.common import flags
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu import native as _native
from ceph_tpu.ec.dispatch import LruCache

__all__ = [
    "XorProgram", "XorSchedule", "compile_matrix", "crc_regions_native",
    "enabled", "execute", "execute_host", "execute_native",
    "host_compile_allowed", "lower_program", "matrix_signature",
    "naive_xor_matmul", "native_available", "native_enabled",
    "prefer_schedule", "reset_stats", "stats",
]


def enabled() -> bool:
    """Schedule-execution kill switch (CEPH_TPU_XSCHED=0 keeps every
    consumer on the naive row-walk — bit-identical output)."""
    return flags.enabled("CEPH_TPU_XSCHED")


def native_enabled() -> bool:
    """Native-executor kill switch (CEPH_TPU_NATIVE_XSCHED=0 pins
    schedule execution to the host tier — bit-identical output)."""
    return flags.enabled("CEPH_TPU_NATIVE_XSCHED")


def native_available() -> bool:
    """True when the fused tape executor may be used: kill switch up
    AND the native library built with xor_sched.cc (a stale cached .so
    or a missing toolchain silently falls back to `execute_host`)."""
    if not native_enabled():
        return False
    lib = _native.get_lib()
    return lib is not None and hasattr(lib, "ceph_tpu_xsched_exec")


def _max_ops() -> int:
    """Op-count ceiling for preferring a schedule on the DEVICE tier:
    past this, the unrolled XOR program stops beating one dense MXU
    matmul dispatch (and the traced graph stops being small)."""
    try:
        return flags.flag_int("CEPH_TPU_XSCHED_MAX_OPS")
    except ValueError:
        return 256


def _min_reduction() -> float:
    """Minimum fractional XOR-count saving before a schedule is worth
    switching lowering for (the measured-op-count pick)."""
    try:
        return flags.flag_float("CEPH_TPU_XSCHED_MIN_REDUCTION")
    except ValueError:
        return 0.25


def _host_max_ones() -> int:
    """Ones-count ceiling for compiling a matrix on the HOST serving
    path: the greedy CSE is pure-Python and quadratic-ish in the
    ones count, and the bitmatrix codecs compile inline (event loop
    / to_thread worker) on first use of each erasure pattern.  The
    default admits the whole legal RAID-6 trio space (worst case,
    liberation k=13 w=13 decode, is ~1.8k ones / ~0.6 s once) while
    refusing pathological hand-rolled geometries that would stall
    the daemon for minutes."""
    try:
        return flags.flag_int("CEPH_TPU_XSCHED_HOST_MAX_ONES")
    except ValueError:
        return 4096


def host_compile_allowed(matrix: np.ndarray) -> bool:
    """True when `matrix` is small enough to compile on the serving
    path (callers above the bound take the naive row-walk)."""
    return int(np.count_nonzero(matrix)) <= _host_max_ones()


# ---------------------------------------------------------------------------
# Signatures (the sha256 identity the plan cache shares)
# ---------------------------------------------------------------------------


def matrix_signature(matrix: np.ndarray, extra: str = "") -> str:
    """Process-stable identity of a generator/decode matrix: sha256
    over shape + buffer (read in place — no tobytes copy) + an
    optional discriminator.  ec/plan.py re-exports this as the
    ExecPlan key prefix; schedules and plans share one identity."""
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    h = hashlib.sha256()
    h.update(repr(m.shape).encode())
    h.update(m.data)
    if extra:
        h.update(extra.encode())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# The compiled artifact
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class XorSchedule:
    """One compiled XOR program.

    References are ints: ``ref < n_in`` names input column ``ref``;
    ``ref >= n_in`` names temporary slot ``ref - n_in``.  ``ops`` are
    executed in order — ``(dst_slot, a, b)`` meaning
    ``tmp[dst_slot] = ref(a) ^ ref(b)`` (slots are REUSED once their
    last reader has run; the order is load-bearing).  ``outputs[r]``
    lists the refs whose XOR is output row r (len 1 = copy, len 0 =
    zero fill)."""

    sig: str
    n_in: int
    n_out: int
    n_slots: int
    ops: Tuple[Tuple[int, int, int], ...]
    outputs: Tuple[Tuple[int, ...], ...]
    xors_naive: int
    xors_scheduled: int

    @property
    def reduction_pct(self) -> float:
        if self.xors_naive <= 0:
            return 0.0
        return 100.0 * (1.0 - self.xors_scheduled / self.xors_naive)


@dataclass(frozen=True)
class XorProgram:
    """A schedule lowered to the native executor's flat op tape.

    The region index space per object: ``[0, n_in)`` input columns,
    ``[n_in, n_in + n_slots)`` reusable temp slots, ``[out_base,
    out_base + n_out)`` output rows — ``n_regions`` uniform regions
    total, so an execution arena is ``(n_objects, n_regions,
    region_bytes)`` contiguous uint8 and the SAME tape replays for
    every packed object.  ``tape`` is C-contiguous int32 ``(n_ops,
    3)`` triples ``(dst, a, b)``: ``b >= 0`` XOR2, ``b == -1`` copy,
    ``b == -2`` accumulate (dst ^= a), ``a == -1`` zero fill —
    exactly native/src/xor_sched.cc's encoding."""

    sig: str
    n_in: int
    n_out: int
    n_slots: int
    n_regions: int
    tape: np.ndarray
    n_ops: int

    @property
    def out_base(self) -> int:
        return self.n_in + self.n_slots


def _lower(sched: XorSchedule) -> XorProgram:
    """Flatten a schedule into the tape.  Schedule refs map to region
    indices IDENTICALLY (ref < n_in is input column ref; ref >= n_in
    is temp slot ref - n_in, which lives at region n_in + slot =
    ref); output row r lands at region out_base + r."""
    n_in, n_slots = sched.n_in, sched.n_slots
    out_base = n_in + n_slots
    ops: List[Tuple[int, int, int]] = []
    for dst, a, b in sched.ops:
        ops.append((n_in + dst, a, b))
    for r, refs in enumerate(sched.outputs):
        dst = out_base + r
        if not refs:
            ops.append((dst, -1, -1))
        elif len(refs) == 1:
            ops.append((dst, refs[0], -1))
        else:
            ops.append((dst, refs[0], refs[1]))
            for extra in refs[2:]:
                ops.append((dst, extra, -2))
    tape = np.ascontiguousarray(np.asarray(ops, dtype=np.int32)
                                .reshape(len(ops), 3))
    tape.setflags(write=False)
    return XorProgram(sig=sched.sig, n_in=n_in, n_out=sched.n_out,
                      n_slots=n_slots,
                      n_regions=out_base + sched.n_out, tape=tape,
                      n_ops=len(ops))


def prefer_schedule(sched: XorSchedule) -> bool:
    """The schedule-vs-matmul pick for device lowerings, by measured
    op count: a schedule wins when it is small enough to unroll AND
    saves at least the configured fraction of the naive XOR count.
    Sparse bitmatrix programs qualify; dense GF(2^8) bit expansions
    (e.g. reed_sol k8m3: hundreds of surviving ops) keep the MXU
    matmul."""
    if not enabled() or sched.xors_naive <= 0:
        return False
    if sched.xors_scheduled > _max_ops():
        return False
    return sched.xors_scheduled <= \
        (1.0 - _min_reduction()) * sched.xors_naive


# ---------------------------------------------------------------------------
# Compilation: Paar CSE + scheduling + slot allocation
# ---------------------------------------------------------------------------


def _paar(rows: List[set], n_in: int) -> List[Tuple[int, int]]:
    """Greedy pairwise CSE: extract the (ref, ref) pair shared by the
    most rows into a new temporary until no pair repeats.  Returns
    the temp definitions; ``rows`` is rewritten in place to reference
    them.  Deterministic: ties break to the lexicographically
    smallest pair."""
    temps: List[Tuple[int, int]] = []
    next_ref = n_in
    while True:
        counts: Dict[Tuple[int, int], int] = {}
        for row in rows:
            if len(row) < 2:
                continue
            srow = sorted(row)
            for i in range(len(srow)):
                for j in range(i + 1, len(srow)):
                    p = (srow[i], srow[j])
                    counts[p] = counts.get(p, 0) + 1
        if not counts:
            break
        best = max(counts.values())
        if best < 2:
            break
        a, b = min(p for p, c in counts.items() if c == best)
        temps.append((a, b))
        t = next_ref
        next_ref += 1
        for row in rows:
            if a in row and b in row:
                row.discard(a)
                row.discard(b)
                row.add(t)
    return temps


def _schedule(temps: List[Tuple[int, int]], rows: List[set],
              n_in: int) -> Tuple[int, tuple, tuple]:
    """The scheduling pass: dependency-DFS emission order from the
    outputs (locality — a temp is computed just before its consumers
    need it, dead temps drop out), then linear-scan slot allocation
    so the executor's live-temporary footprint is ``n_slots``, not
    ``len(temps)``.  Returns (n_slots, ops, outputs) in slot-space
    references."""
    order: List[int] = []
    seen: set = set()
    for row in rows:
        for want in sorted(row):
            if want < n_in:
                continue
            stack = [want]
            while stack:
                ref = stack[-1]
                if ref in seen or ref < n_in:
                    stack.pop()
                    continue
                deps = [s for s in temps[ref - n_in]
                        if s >= n_in and s not in seen]
                if deps:
                    stack.extend(deps)
                    continue
                seen.add(ref)
                order.append(ref)
                stack.pop()
    t_count = len(order)
    # last use of each temp on the (temps..., then outputs...) timeline
    last: Dict[int, int] = {}
    for t, ref in enumerate(order):
        for s in temps[ref - n_in]:
            if s >= n_in:
                last[s] = t
    for r, row in enumerate(rows):
        for s in row:
            if s >= n_in:
                last[s] = t_count + r
    by_time: Dict[int, List[int]] = {}
    for ref, t in last.items():
        by_time.setdefault(t, []).append(ref)
    free: List[int] = []
    slot_of: Dict[int, int] = {}
    n_slots = 0
    ops: List[Tuple[int, int, int]] = []

    def resolve(s: int) -> int:
        return s if s < n_in else n_in + slot_of[s]

    for t, ref in enumerate(order):
        a, b = temps[ref - n_in]
        ra, rb = resolve(a), resolve(b)
        # a temp last READ here may donate its slot as this op's dst:
        # XOR with out= aliasing an operand exactly is well-defined
        for dead in sorted(by_time.get(t, ())):
            free.append(slot_of[dead])
        if free:
            dst = free.pop()
        else:
            dst = n_slots
            n_slots += 1
        slot_of[ref] = dst
        ops.append((dst, ra, rb))
    outputs = tuple(tuple(sorted(resolve(s) for s in row))
                    for row in rows)
    return n_slots, tuple(ops), outputs


def _compile(bm: np.ndarray, sig: str) -> XorSchedule:
    n_out, n_in = bm.shape
    rows = [set(np.flatnonzero(bm[r]).tolist()) for r in range(n_out)]
    xors_naive = sum(max(len(row) - 1, 0) for row in rows)
    temps = _paar(rows, n_in)
    n_slots, ops, outputs = _schedule(temps, rows, n_in)
    xors_scheduled = len(ops) + sum(max(len(row) - 1, 0)
                                    for row in outputs)
    return XorSchedule(sig=sig, n_in=n_in, n_out=n_out,
                       n_slots=n_slots, ops=ops, outputs=outputs,
                       xors_naive=xors_naive,
                       xors_scheduled=xors_scheduled)


# ---------------------------------------------------------------------------
# Memoization + stats
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cache = LruCache(cap=256)
_counters: Dict[str, int] = {"compiled": 0, "cache_hits": 0,
                             "xors_naive": 0, "xors_scheduled": 0,
                             "tape_hits": 0, "tape_misses": 0,
                             "exec_native": 0, "exec_host": 0}


def compile_matrix(bm: np.ndarray,
                   sig: Optional[str] = None) -> XorSchedule:
    """Compile (or fetch) the XOR schedule of a (R, C) GF(2) 0/1
    matrix.  ``sig`` lets callers that already hold the matrix's
    sha256 identity (plan.codec_signature / matrix_signature) skip
    the rehash — it MUST be matrix-unique; omitted, the content
    signature is computed here.  Schedules survive plan rebuilds:
    this cache is keyed by matrix identity, not by device set or
    bucketed shape, and ec/plan.py's clear()/quarantine never touch
    it."""
    bm = np.ascontiguousarray(bm, dtype=np.uint8)
    key = sig or matrix_signature(bm)
    with _lock:
        hit = _cache.peek(key)
        if hit is not None:
            _counters["cache_hits"] += 1
            return hit
    sched = _compile(bm, key)
    with _lock:
        again = _cache.peek(key)
        if again is not None:       # racing compile: first one wins
            _counters["cache_hits"] += 1
            return again
        _cache.put(key, sched)
        _counters["compiled"] += 1
        _counters["xors_naive"] += sched.xors_naive
        _counters["xors_scheduled"] += sched.xors_scheduled
    return sched


def lower_program(sched: XorSchedule) -> XorProgram:
    """The native tape of a schedule, memoized ALONGSIDE it in the
    same signature-keyed cache (key ``sig + "/tape"``): lowering
    happens once per matrix identity, and `clear()` drops schedules
    and tapes together.  `stats()` counts tape hits/misses separately
    from schedule-cache traffic so the bench attribution can name
    where a small-op win came from."""
    key = sched.sig + "/tape"
    with _lock:
        hit = _cache.peek(key)
        if hit is not None:
            _counters["tape_hits"] += 1
            return hit
    prog = _lower(sched)
    with _lock:
        again = _cache.peek(key)
        if again is not None:       # racing lowering: first one wins
            _counters["tape_hits"] += 1
            return again
        _cache.put(key, prog)
        _counters["tape_misses"] += 1
    return prog


def stats() -> dict:
    """The `xsched` observability section plan.stats() embeds."""
    with _lock:
        out = dict(_counters)
        out["cached"] = len(_cache)
    out["enabled"] = enabled()
    out["native_enabled"] = native_enabled()
    return out


def reset_stats() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


def clear() -> None:
    """Drop memoized schedules (tests only — production relies on
    survival across plan rebuilds)."""
    with _lock:
        _cache.clear()


# ---------------------------------------------------------------------------
# Executors
# ---------------------------------------------------------------------------


def execute_host(sched: XorSchedule, sources: Sequence[np.ndarray],
                 outs: Sequence[np.ndarray]) -> None:
    """Run the XOR program over numpy regions, in place.

    ``sources[c]`` is input column c — any same-shape uint8 views
    (the bitmatrix packet views; strided is fine).  ``outs[r]`` is
    the writable destination for output row r.  Outputs must not
    alias sources (the codec layers write parity/recovered chunks,
    never their inputs).  Temporaries are ``n_slots`` scratch
    buffers allocated here per call."""
    with _lock:
        _counters["exec_host"] += 1
    n_in = sched.n_in
    tmp: List[Optional[np.ndarray]] = [None] * sched.n_slots

    def ref(r: int) -> np.ndarray:
        return sources[r] if r < n_in else tmp[r - n_in]

    for dst, a, b in sched.ops:
        if tmp[dst] is None:
            tmp[dst] = np.bitwise_xor(ref(a), ref(b))
        else:
            np.bitwise_xor(ref(a), ref(b), out=tmp[dst])
    for refs, out in zip(sched.outputs, outs):
        if not refs:
            out[...] = 0
        elif len(refs) == 1:
            out[...] = ref(refs[0])
        else:
            np.bitwise_xor(ref(refs[0]), ref(refs[1]), out=out)
            for r in refs[2:]:
                np.bitwise_xor(out, ref(r), out=out)


_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)
_U32P = ctypes.POINTER(ctypes.c_uint32)


def execute_native(prog: XorProgram, arena: np.ndarray) -> None:
    """Run the whole op tape in ONE native call.

    ``arena`` is ``(n_objects, n_regions, region_bytes)`` — or 2-D
    ``(n_regions, region_bytes)`` for a single object — C-contiguous
    uint8, input regions filled by the caller; temps and outputs are
    produced in place.  The same tape replays for every object, so a
    packed batch of thousands of tiny objects is one Python→native
    transition total."""
    if arena.ndim == 2:
        n_objects, (n_regions, rbytes) = 1, arena.shape
    else:
        n_objects, n_regions, rbytes = arena.shape
    if n_regions != prog.n_regions:
        raise ValueError(
            f"arena has {n_regions} regions, program needs "
            f"{prog.n_regions}")
    if not arena.flags.c_contiguous or arena.dtype != np.uint8:
        raise ValueError("arena must be C-contiguous uint8")
    lib = _native.get_lib()
    lib.ceph_tpu_xsched_exec(
        prog.tape.ctypes.data_as(_I32P), prog.n_ops,
        arena.ctypes.data_as(_U8P), n_regions, rbytes, n_objects)
    with _lock:
        _counters["exec_native"] += 1


def crc_regions_native(arena: np.ndarray, spans: np.ndarray,
                       crcs: np.ndarray) -> None:
    """Fold contiguous region spans of a FLAT arena into crc32c
    accumulators natively: ``spans`` is ``(n, 3)`` int32 rows
    ``(region_start, region_count, crc_slot)`` over the flattened
    ``(total_regions, region_bytes)`` view of ``arena``; ``crcs`` is
    the uint32 accumulator vector (callers seed it — HashInfo uses
    0xFFFFFFFF).  Spans fold in order, so a multi-stripe shard
    accumulates stripe by stripe exactly like ``HashInfo.append``."""
    flat = arena.reshape(-1, arena.shape[-1])
    spans = np.ascontiguousarray(spans, dtype=np.int32)
    if not flat.flags.c_contiguous:
        raise ValueError("arena must be C-contiguous")
    if not crcs.flags.c_contiguous or crcs.dtype != np.uint32:
        raise ValueError("crcs must be C-contiguous uint32")
    lib = _native.get_lib()
    lib.ceph_tpu_xsched_crc_spans(
        flat.ctypes.data_as(_U8P), flat.shape[1],
        spans.ctypes.data_as(_I32P), spans.shape[0],
        crcs.ctypes.data_as(_U32P))


def execute(sched: XorSchedule, sources: Sequence[np.ndarray],
            outs: Sequence[np.ndarray]) -> str:
    """The tier seam: run the program natively when the fused executor
    is built and enabled, else `execute_host` — same signature, same
    bytes, returns which tier ran ("native" / "host").

    The native path packs sources into a fresh region arena and
    copies outputs back out (two extra passes over the data — far
    cheaper than one numpy dispatch per XOR in the small-op band);
    callers that control their own layout (bitmatrix chunk packing,
    the encode service's multi-object arenas) skip these copies by
    calling `lower_program` + `execute_native` directly."""
    if native_available() and len(sources):
        rbytes = int(sources[0].nbytes)
        if all(int(s.nbytes) == rbytes for s in sources):
            prog = lower_program(sched)
            arena = np.empty((prog.n_regions, rbytes), dtype=np.uint8)
            for c, src in enumerate(sources):
                arena[c].reshape(src.shape)[...] = src
            execute_native(prog, arena)
            base = prog.out_base
            for r, out in enumerate(outs):
                out[...] = arena[base + r].reshape(out.shape)
            return "native"
    execute_host(sched, sources, outs)
    return "host"


def naive_xor_matmul(rows: np.ndarray,
                     packets: np.ndarray) -> np.ndarray:
    """(R, C) 0/1 x (B, C, ps) byte packets -> (B, R, ps) XORs — the
    unscheduled row-walk.  This is the kill-switch fallback and the
    independent bit-exactness oracle for every schedule; the
    `unscheduled-bitmatrix-xor` lint rule pins naive walks like this
    to ec/xsched.py + ec/plan.py."""
    b, _c, ps = packets.shape
    out = np.zeros((b, rows.shape[0], ps), dtype=np.uint8)
    for r in range(rows.shape[0]):
        idx = np.flatnonzero(rows[r])
        if idx.size:
            out[:, r] = np.bitwise_xor.reduce(packets[:, idx, :],
                                              axis=1)
    return out

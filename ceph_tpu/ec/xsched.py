"""Plan-time XOR-schedule compiler for the GF(2) bitmatrix family.

The XOR-EC program-optimization literature (arXiv:2108.02692) treats
an erasure code's bit matrix as a PROGRAM, not an operand: every
output row is an XOR of input columns, shared sub-XORs can be
computed once (common-subexpression elimination), the resulting ops
can be scheduled for temporary locality, and the whole compiled
artifact memoized — cutting the XOR count 30-60% before a single
byte moves.  This module is that compiler:

* **CSE pass** — greedy pairwise extraction (Paar's algorithm):
  repeatedly find the column pair shared by the most output rows,
  hoist it into a temporary, and substitute.  Each extraction with
  multiplicity c saves c-1 XOR region ops.
* **Scheduling pass** — temporaries are emitted in dependency-DFS
  order from the outputs (producers land next to their consumers),
  then a linear-scan allocator maps them onto a bounded set of
  reusable buffer slots: `n_slots` — the live-temporary bound — is
  what the executor must allocate, not the temp count.
* **Memoization** — compiled schedules are cached in a bounded LRU
  keyed by the same sha256 matrix/codec signature the ExecPlan cache
  uses (`matrix_signature` lives HERE and ec/plan.py re-exports it);
  decode schedules key per erasure-pattern submatrix content, so a
  re-instantiated codec or a rebuilt plan (mesh shrink, quarantine)
  never recompiles a known matrix.

Two executors lower a schedule:

* the HOST tier (`execute_host`) runs the program over numpy buffer
  views — the bitmatrix trio's packet regions (models/bitmatrix
  `packet_views`) execute in place with zero stacking/transpose
  copies; and
* the DEVICE tier lives in ec/plan.py as the `xor_sched` plan kind
  (the same program over bit planes, traced next to the
  `_gf2_matmul_bytes_impl` matmul lowering) — consumers pick
  schedule-vs-matmul by the measured op count (`prefer_schedule`).

Kill switch: CEPH_TPU_XSCHED=0 pins every caller to the naive
row-walk (`naive_xor_matmul`, bit-identical output).  Stats land in
`plan.stats()["xsched"]` — schedules compiled, cache hits,
xors_naive vs xors_scheduled.

This module must stay importable without jax (the host tier is pure
numpy) and must not import ec/plan.py (plan imports us).
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.ec.dispatch import LruCache

__all__ = [
    "XorSchedule", "compile_matrix", "enabled", "execute_host",
    "matrix_signature", "naive_xor_matmul", "prefer_schedule",
    "reset_stats", "stats",
]


def enabled() -> bool:
    """Schedule-execution kill switch (CEPH_TPU_XSCHED=0 keeps every
    consumer on the naive row-walk — bit-identical output)."""
    return os.environ.get("CEPH_TPU_XSCHED", "1") != "0"


def _max_ops() -> int:
    """Op-count ceiling for preferring a schedule on the DEVICE tier:
    past this, the unrolled XOR program stops beating one dense MXU
    matmul dispatch (and the traced graph stops being small)."""
    try:
        return int(os.environ.get("CEPH_TPU_XSCHED_MAX_OPS", "256"))
    except ValueError:
        return 256


def _min_reduction() -> float:
    """Minimum fractional XOR-count saving before a schedule is worth
    switching lowering for (the measured-op-count pick)."""
    try:
        return float(os.environ.get("CEPH_TPU_XSCHED_MIN_REDUCTION",
                                    "0.25"))
    except ValueError:
        return 0.25


def _host_max_ones() -> int:
    """Ones-count ceiling for compiling a matrix on the HOST serving
    path: the greedy CSE is pure-Python and quadratic-ish in the
    ones count, and the bitmatrix codecs compile inline (event loop
    / to_thread worker) on first use of each erasure pattern.  The
    default admits the whole legal RAID-6 trio space (worst case,
    liberation k=13 w=13 decode, is ~1.8k ones / ~0.6 s once) while
    refusing pathological hand-rolled geometries that would stall
    the daemon for minutes."""
    try:
        return int(os.environ.get("CEPH_TPU_XSCHED_HOST_MAX_ONES",
                                  "4096"))
    except ValueError:
        return 4096


def host_compile_allowed(matrix: np.ndarray) -> bool:
    """True when `matrix` is small enough to compile on the serving
    path (callers above the bound take the naive row-walk)."""
    return int(np.count_nonzero(matrix)) <= _host_max_ones()


# ---------------------------------------------------------------------------
# Signatures (the sha256 identity the plan cache shares)
# ---------------------------------------------------------------------------


def matrix_signature(matrix: np.ndarray, extra: str = "") -> str:
    """Process-stable identity of a generator/decode matrix: sha256
    over shape + buffer (read in place — no tobytes copy) + an
    optional discriminator.  ec/plan.py re-exports this as the
    ExecPlan key prefix; schedules and plans share one identity."""
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    h = hashlib.sha256()
    h.update(repr(m.shape).encode())
    h.update(m.data)
    if extra:
        h.update(extra.encode())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# The compiled artifact
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class XorSchedule:
    """One compiled XOR program.

    References are ints: ``ref < n_in`` names input column ``ref``;
    ``ref >= n_in`` names temporary slot ``ref - n_in``.  ``ops`` are
    executed in order — ``(dst_slot, a, b)`` meaning
    ``tmp[dst_slot] = ref(a) ^ ref(b)`` (slots are REUSED once their
    last reader has run; the order is load-bearing).  ``outputs[r]``
    lists the refs whose XOR is output row r (len 1 = copy, len 0 =
    zero fill)."""

    sig: str
    n_in: int
    n_out: int
    n_slots: int
    ops: Tuple[Tuple[int, int, int], ...]
    outputs: Tuple[Tuple[int, ...], ...]
    xors_naive: int
    xors_scheduled: int

    @property
    def reduction_pct(self) -> float:
        if self.xors_naive <= 0:
            return 0.0
        return 100.0 * (1.0 - self.xors_scheduled / self.xors_naive)


def prefer_schedule(sched: XorSchedule) -> bool:
    """The schedule-vs-matmul pick for device lowerings, by measured
    op count: a schedule wins when it is small enough to unroll AND
    saves at least the configured fraction of the naive XOR count.
    Sparse bitmatrix programs qualify; dense GF(2^8) bit expansions
    (e.g. reed_sol k8m3: hundreds of surviving ops) keep the MXU
    matmul."""
    if not enabled() or sched.xors_naive <= 0:
        return False
    if sched.xors_scheduled > _max_ops():
        return False
    return sched.xors_scheduled <= \
        (1.0 - _min_reduction()) * sched.xors_naive


# ---------------------------------------------------------------------------
# Compilation: Paar CSE + scheduling + slot allocation
# ---------------------------------------------------------------------------


def _paar(rows: List[set], n_in: int) -> List[Tuple[int, int]]:
    """Greedy pairwise CSE: extract the (ref, ref) pair shared by the
    most rows into a new temporary until no pair repeats.  Returns
    the temp definitions; ``rows`` is rewritten in place to reference
    them.  Deterministic: ties break to the lexicographically
    smallest pair."""
    temps: List[Tuple[int, int]] = []
    next_ref = n_in
    while True:
        counts: Dict[Tuple[int, int], int] = {}
        for row in rows:
            if len(row) < 2:
                continue
            srow = sorted(row)
            for i in range(len(srow)):
                for j in range(i + 1, len(srow)):
                    p = (srow[i], srow[j])
                    counts[p] = counts.get(p, 0) + 1
        if not counts:
            break
        best = max(counts.values())
        if best < 2:
            break
        a, b = min(p for p, c in counts.items() if c == best)
        temps.append((a, b))
        t = next_ref
        next_ref += 1
        for row in rows:
            if a in row and b in row:
                row.discard(a)
                row.discard(b)
                row.add(t)
    return temps


def _schedule(temps: List[Tuple[int, int]], rows: List[set],
              n_in: int) -> Tuple[int, tuple, tuple]:
    """The scheduling pass: dependency-DFS emission order from the
    outputs (locality — a temp is computed just before its consumers
    need it, dead temps drop out), then linear-scan slot allocation
    so the executor's live-temporary footprint is ``n_slots``, not
    ``len(temps)``.  Returns (n_slots, ops, outputs) in slot-space
    references."""
    order: List[int] = []
    seen: set = set()
    for row in rows:
        for want in sorted(row):
            if want < n_in:
                continue
            stack = [want]
            while stack:
                ref = stack[-1]
                if ref in seen or ref < n_in:
                    stack.pop()
                    continue
                deps = [s for s in temps[ref - n_in]
                        if s >= n_in and s not in seen]
                if deps:
                    stack.extend(deps)
                    continue
                seen.add(ref)
                order.append(ref)
                stack.pop()
    t_count = len(order)
    # last use of each temp on the (temps..., then outputs...) timeline
    last: Dict[int, int] = {}
    for t, ref in enumerate(order):
        for s in temps[ref - n_in]:
            if s >= n_in:
                last[s] = t
    for r, row in enumerate(rows):
        for s in row:
            if s >= n_in:
                last[s] = t_count + r
    by_time: Dict[int, List[int]] = {}
    for ref, t in last.items():
        by_time.setdefault(t, []).append(ref)
    free: List[int] = []
    slot_of: Dict[int, int] = {}
    n_slots = 0
    ops: List[Tuple[int, int, int]] = []

    def resolve(s: int) -> int:
        return s if s < n_in else n_in + slot_of[s]

    for t, ref in enumerate(order):
        a, b = temps[ref - n_in]
        ra, rb = resolve(a), resolve(b)
        # a temp last READ here may donate its slot as this op's dst:
        # XOR with out= aliasing an operand exactly is well-defined
        for dead in sorted(by_time.get(t, ())):
            free.append(slot_of[dead])
        if free:
            dst = free.pop()
        else:
            dst = n_slots
            n_slots += 1
        slot_of[ref] = dst
        ops.append((dst, ra, rb))
    outputs = tuple(tuple(sorted(resolve(s) for s in row))
                    for row in rows)
    return n_slots, tuple(ops), outputs


def _compile(bm: np.ndarray, sig: str) -> XorSchedule:
    n_out, n_in = bm.shape
    rows = [set(np.flatnonzero(bm[r]).tolist()) for r in range(n_out)]
    xors_naive = sum(max(len(row) - 1, 0) for row in rows)
    temps = _paar(rows, n_in)
    n_slots, ops, outputs = _schedule(temps, rows, n_in)
    xors_scheduled = len(ops) + sum(max(len(row) - 1, 0)
                                    for row in outputs)
    return XorSchedule(sig=sig, n_in=n_in, n_out=n_out,
                       n_slots=n_slots, ops=ops, outputs=outputs,
                       xors_naive=xors_naive,
                       xors_scheduled=xors_scheduled)


# ---------------------------------------------------------------------------
# Memoization + stats
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cache = LruCache(cap=256)
_counters: Dict[str, int] = {"compiled": 0, "cache_hits": 0,
                             "xors_naive": 0, "xors_scheduled": 0}


def compile_matrix(bm: np.ndarray,
                   sig: Optional[str] = None) -> XorSchedule:
    """Compile (or fetch) the XOR schedule of a (R, C) GF(2) 0/1
    matrix.  ``sig`` lets callers that already hold the matrix's
    sha256 identity (plan.codec_signature / matrix_signature) skip
    the rehash — it MUST be matrix-unique; omitted, the content
    signature is computed here.  Schedules survive plan rebuilds:
    this cache is keyed by matrix identity, not by device set or
    bucketed shape, and ec/plan.py's clear()/quarantine never touch
    it."""
    bm = np.ascontiguousarray(bm, dtype=np.uint8)
    key = sig or matrix_signature(bm)
    with _lock:
        hit = _cache.peek(key)
        if hit is not None:
            _counters["cache_hits"] += 1
            return hit
    sched = _compile(bm, key)
    with _lock:
        again = _cache.peek(key)
        if again is not None:       # racing compile: first one wins
            _counters["cache_hits"] += 1
            return again
        _cache.put(key, sched)
        _counters["compiled"] += 1
        _counters["xors_naive"] += sched.xors_naive
        _counters["xors_scheduled"] += sched.xors_scheduled
    return sched


def stats() -> dict:
    """The `xsched` observability section plan.stats() embeds."""
    with _lock:
        out = dict(_counters)
        out["cached"] = len(_cache)
    out["enabled"] = enabled()
    return out


def reset_stats() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


def clear() -> None:
    """Drop memoized schedules (tests only — production relies on
    survival across plan rebuilds)."""
    with _lock:
        _cache.clear()


# ---------------------------------------------------------------------------
# Executors
# ---------------------------------------------------------------------------


def execute_host(sched: XorSchedule, sources: Sequence[np.ndarray],
                 outs: Sequence[np.ndarray]) -> None:
    """Run the XOR program over numpy regions, in place.

    ``sources[c]`` is input column c — any same-shape uint8 views
    (the bitmatrix packet views; strided is fine).  ``outs[r]`` is
    the writable destination for output row r.  Outputs must not
    alias sources (the codec layers write parity/recovered chunks,
    never their inputs).  Temporaries are ``n_slots`` scratch
    buffers allocated here per call."""
    n_in = sched.n_in
    tmp: List[Optional[np.ndarray]] = [None] * sched.n_slots

    def ref(r: int) -> np.ndarray:
        return sources[r] if r < n_in else tmp[r - n_in]

    for dst, a, b in sched.ops:
        if tmp[dst] is None:
            tmp[dst] = np.bitwise_xor(ref(a), ref(b))
        else:
            np.bitwise_xor(ref(a), ref(b), out=tmp[dst])
    for refs, out in zip(sched.outputs, outs):
        if not refs:
            out[...] = 0
        elif len(refs) == 1:
            out[...] = ref(refs[0])
        else:
            np.bitwise_xor(ref(refs[0]), ref(refs[1]), out=out)
            for r in refs[2:]:
                np.bitwise_xor(out, ref(r), out=out)


def naive_xor_matmul(rows: np.ndarray,
                     packets: np.ndarray) -> np.ndarray:
    """(R, C) 0/1 x (B, C, ps) byte packets -> (B, R, ps) XORs — the
    unscheduled row-walk.  This is the kill-switch fallback and the
    independent bit-exactness oracle for every schedule; the
    `unscheduled-bitmatrix-xor` lint rule pins naive walks like this
    to ec/xsched.py + ec/plan.py."""
    b, _c, ps = packets.shape
    out = np.zeros((b, rows.shape[0], ps), dtype=np.uint8)
    for r in range(rows.shape[0]):
        idx = np.flatnonzero(rows[r])
        if idx.size:
            out[:, r] = np.bitwise_xor.reduce(packets[:, idx, :],
                                              axis=1)
    return out

"""LRC — layered locally-repairable erasure code.

Reference parity: the lrc plugin
(/root/reference/src/erasure-code/lrc/ErasureCodeLrc.{h,cc}):

- the code is a stack of layers, each a sub-erasure-code applied to the
  subset of chunk positions marked in its `chunks_map` string ('D' data,
  'c' coding, '_' not in this layer);
- `k,m,l` shorthand generates mapping/layers/crush-steps
  (parse_kml ErasureCodeLrc.cc:293-397): (k+m)/l groups, one global layer
  plus one local-parity layer per group — total k+m+(k+m)/l chunks;
- encode applies layers top-down starting from the topmost layer that
  covers want_to_encode (encode_chunks :662-700);
- decode walks layers bottom-up (reverse), each layer recovering what it
  can into `decoded` so upper layers can reuse it (decode_chunks :702-780);
- minimum_to_decode picks the cheapest covering layers, falling back to
  cascaded recovery (3-case algorithm, _minimum_to_decode :135-289);
- crush rule from `crush-steps` (one choose step per locality level).

Sub-codecs default to plugin=jerasure technique=reed_sol_van — which this
framework aliases to the TPU codec — so every layer's matmul runs on the
MXU via ErasureCodeJax.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Set

from ceph_tpu.crush.map import Rule, RuleStep
from ceph_tpu.crush.mapper import (
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_TAKE,
)
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError

DEFAULT_KML = "-1"


@dataclass
class Layer:
    chunks_map: str
    profile: Dict[str, str] = field(default_factory=dict)
    data: List[int] = field(default_factory=list)
    coding: List[int] = field(default_factory=list)
    chunks: List[int] = field(default_factory=list)
    chunks_as_set: Set[int] = field(default_factory=set)
    erasure_code: Optional[ErasureCode] = None


@dataclass
class Step:
    op: str
    type: str
    n: int


def _parse_layers_json(text: str):
    """json_spirit tolerates trailing commas (the kml generator emits
    them); strip them before handing to the stdlib parser."""
    cleaned = re.sub(r",\s*([\]}])", r"\1", text)
    try:
        return json.loads(cleaned)
    except json.JSONDecodeError as e:
        raise ErasureCodeError(22, f"invalid layers JSON: {e}")


class ErasureCodeLrc(ErasureCode):
    def __init__(self) -> None:
        super().__init__()
        self.layers: List[Layer] = []
        self.chunk_count_ = 0
        self.data_chunk_count_ = 0
        self.rule_steps: List[Step] = [Step("chooseleaf", "host", 0)]

    # -- profile parsing --------------------------------------------------

    def init(self, profile: Dict[str, str]) -> None:
        self._parse_kml(profile)
        self._parse_rule(profile)
        if "layers" not in profile:
            raise ErasureCodeError(
                22, "could not find 'layers' in profile")
        if "mapping" not in profile:
            raise ErasureCodeError(
                22, "the 'mapping' profile is required with 'layers'")
        description = _parse_layers_json(profile["layers"])
        if not isinstance(description, list):
            raise ErasureCodeError(22, "layers must be a JSON array")
        self._layers_parse(description)
        self._layers_init()
        self._layers_sanity_checks(profile)

        mapping = profile["mapping"]
        self.chunk_count_ = len(mapping)
        self.data_chunk_count_ = mapping.count("D")
        self.k = self.data_chunk_count_
        self.m = self.chunk_count_ - self.k
        super().init(profile)

    def _parse_kml(self, profile: Dict[str, str]) -> None:
        """k/m/l shorthand -> mapping + layers + crush-steps
        (ErasureCodeLrc.cc:293-397)."""
        vals = {}
        for name in ("k", "m", "l"):
            raw = profile.get(name, DEFAULT_KML) or DEFAULT_KML
            try:
                vals[name] = int(raw)
            except ValueError:
                raise ErasureCodeError(22, f"{name}={raw} is not an int")
        k, m, l = vals["k"], vals["m"], vals["l"]
        if k == -1 and m == -1 and l == -1:
            return
        if -1 in (k, m, l):
            raise ErasureCodeError(
                22, "all of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ErasureCodeError(
                    22, f"the {generated} parameter cannot be set when"
                    " k, m, l are set")
        if l == 0 or (k + m) % l:
            raise ErasureCodeError(22, "k + m must be a multiple of l")
        groups = (k + m) // l
        if k % groups:
            raise ErasureCodeError(22, "k must be a multiple of (k + m) / l")
        if m % groups:
            raise ErasureCodeError(22, "m must be a multiple of (k + m) / l")

        kg, mg = k // groups, m // groups
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * groups

        layers = [[("D" * kg + "c" * mg + "_") * groups, ""]]
        for i in range(groups):
            row = "".join(
                ("D" * l + "c") if i == j else "_" * (l + 1)
                for j in range(groups))
            layers.append([row, ""])
        profile["layers"] = json.dumps(layers)

        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host")
        if locality:
            self.rule_steps = [Step("choose", locality, groups),
                               Step("chooseleaf", failure_domain, l + 1)]
        elif failure_domain:
            self.rule_steps = [Step("chooseleaf", failure_domain, 0)]

    def _parse_rule(self, profile: Dict[str, str]) -> None:
        self.rule_root = profile.setdefault("crush-root", "default")
        self.rule_device_class = profile.setdefault("crush-device-class", "")
        if "crush-steps" in profile:
            steps = _parse_layers_json(profile["crush-steps"])
            if not isinstance(steps, list):
                raise ErasureCodeError(22, "crush-steps must be a JSON array")
            self.rule_steps = []
            for entry in steps:
                if (not isinstance(entry, list) or len(entry) != 3 or
                        not isinstance(entry[0], str) or
                        not isinstance(entry[1], str)):
                    raise ErasureCodeError(
                        22, f"crush-steps entry {entry!r} must be"
                        " [op, type, n]")
                self.rule_steps.append(Step(entry[0], entry[1], int(entry[2])))

    def _layers_parse(self, description) -> None:
        for position, layer_json in enumerate(description):
            if not isinstance(layer_json, list) or not layer_json:
                raise ErasureCodeError(
                    22, f"layers[{position}] must be a non-empty JSON array")
            chunks_map = layer_json[0]
            if not isinstance(chunks_map, str):
                raise ErasureCodeError(
                    22, f"layers[{position}][0] must be a string")
            layer = Layer(chunks_map=chunks_map)
            if len(layer_json) > 1:
                spec = layer_json[1]
                if isinstance(spec, str):
                    # "k=4 technique=..." style word list
                    for word in spec.split():
                        if "=" not in word:
                            raise ErasureCodeError(
                                22, f"expected key=value got {word!r}")
                        key, val = word.split("=", 1)
                        layer.profile[key] = val
                elif isinstance(spec, dict):
                    layer.profile.update(
                        {str(kk): str(vv) for kk, vv in spec.items()})
                else:
                    raise ErasureCodeError(
                        22, f"layers[{position}][1] must be a string or"
                        " object")
            self.layers.append(layer)

    def _layers_init(self) -> None:
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry

        registry = ErasureCodePluginRegistry.instance()
        for layer in self.layers:
            for position, ch in enumerate(layer.chunks_map):
                if ch == "D":
                    layer.data.append(position)
                elif ch == "c":
                    layer.coding.append(position)
                if ch in ("D", "c"):
                    layer.chunks_as_set.add(position)
            layer.chunks = layer.data + layer.coding
            layer.profile.setdefault("k", str(len(layer.data)))
            layer.profile.setdefault("m", str(len(layer.coding)))
            layer.profile.setdefault("plugin", "jerasure")
            layer.profile.setdefault("technique", "reed_sol_van")
            layer.erasure_code = registry.factory(
                layer.profile["plugin"], dict(layer.profile))

    def _layers_sanity_checks(self, profile: Dict[str, str]) -> None:
        if not self.layers:
            raise ErasureCodeError(
                22, "at least one layer is required")
        mapping = profile["mapping"]
        for i, layer in enumerate(self.layers):
            if len(layer.chunks_map) != len(mapping):
                raise ErasureCodeError(
                    22, f"layer {i} map {layer.chunks_map!r} has length"
                    f" {len(layer.chunks_map)}, expected {len(mapping)}")

    # -- geometry ---------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count_

    def get_coding_chunk_count(self) -> int:
        return self.chunk_count_ - self.data_chunk_count_

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    def get_alignment(self) -> int:
        return self.layers[0].erasure_code.get_alignment()

    # -- encode / decode --------------------------------------------------

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        # start at the topmost layer that covers everything wanted
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if want_to_encode <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_encoded = {j: encoded[c]
                             for j, c in enumerate(layer.chunks)}
            layer_want = {j for j, c in enumerate(layer.chunks)
                          if c in want_to_encode}
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)
            for j, c in enumerate(layer.chunks):
                encoded[c] = layer_encoded[j]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        available = set(chunks)
        erasures = {i for i in range(self.get_chunk_count())
                    if i not in available}
        want_erasures = erasures & set(want_to_read)

        # The reference walks the layers once in reverse (locals first,
        # then global), which cannot recover cascades in the opposite
        # direction (e.g. global repairs a chunk that then lets a local
        # layer repair its parity).  Iterating to a fixpoint strictly
        # extends recoverability at no cost in the common single-pass case.
        progress = True
        while want_erasures and progress:
            progress = False
            for layer in reversed(self.layers):
                if self._decode_one_layer(layer, want_to_read, erasures,
                                          decoded):
                    progress = True
                want_erasures = erasures & set(want_to_read)
                if not want_erasures:
                    break

        if want_erasures:
            raise ErasureCodeError(
                5, f"unable to read {sorted(want_erasures)} from available"
                f" {sorted(available)}")

    def _decode_one_layer(self, layer: Layer, want_to_read: Set[int],
                          erasures: Set[int],
                          decoded: Dict[int, bytearray]) -> bool:
        """One layer's recovery attempt; True if it repaired anything."""
        layer_erasures = layer.chunks_as_set & erasures
        if not layer_erasures:
            return False  # nothing to do here
        if len(layer_erasures) > layer.erasure_code.get_coding_chunk_count():
            return False  # too many erasures for this layer
        layer_chunks = {}
        layer_decoded = {}
        layer_want = set()
        for j, c in enumerate(layer.chunks):
            # pick from `decoded` (not `chunks`) so chunks recovered by
            # other layers feed this one
            if c not in erasures:
                # view, not a copy: the inner decode stacks/consumes
                # the buffer before any later layer mutates it
                layer_chunks[j] = memoryview(decoded[c])
            if c in want_to_read or c in layer_erasures:
                layer_want.add(j)
            layer_decoded[j] = decoded[c]
        layer.erasure_code.decode_chunks(
            layer_want, layer_chunks, layer_decoded)
        for j, c in enumerate(layer.chunks):
            decoded[c] = layer_decoded[j]
            erasures.discard(c)
        return True

    # -- decode planning (the 3-case algorithm) ---------------------------

    def _minimum_to_decode(self, want_to_read: Set[int],
                           available_chunks: Set[int]) -> Set[int]:
        all_chunks = set(range(self.get_chunk_count()))
        erasures_total = all_chunks - available_chunks
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & want_to_read

        # Case 1: nothing wanted is missing.
        if not erasures_want:
            return set(want_to_read)

        # Case 2: recover wanted erasures with as few chunks as possible,
        # trying small (local) layers first — layers are walked in reverse,
        # and kml puts locals after the global layer.
        minimum: Set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                minimum |= layer_want
                continue
            erasures = layer.chunks_as_set & erasures_not_recovered
            if len(erasures) > layer.erasure_code.get_coding_chunk_count():
                continue  # hope an upper layer does better
            minimum |= layer.chunks_as_set - erasures_not_recovered
            erasures_not_recovered -= erasures
            erasures_want -= erasures
        if not erasures_want:
            minimum |= want_to_read
            minimum -= erasures_total
            return minimum

        # Case 3: cascade — recover anything recoverable anywhere, hoping it
        # unlocks the upper layers; if everything is reachable, read all
        # available chunks.
        # (fixpoint, like decode_chunks — strictly more patterns than the
        # reference's single pass)
        remaining = set(erasures_total)
        progress = True
        while remaining and progress:
            progress = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_as_set & remaining
                if not layer_erasures:
                    continue
                if (len(layer_erasures)
                        <= layer.erasure_code.get_coding_chunk_count()):
                    remaining -= layer_erasures
                    progress = True
        if not remaining:
            return set(available_chunks)

        raise ErasureCodeError(
            5, f"not enough chunks in {sorted(available_chunks)} to read"
            f" {sorted(want_to_read)}")

    # -- CRUSH ------------------------------------------------------------

    def create_rule(self, name: str, crush) -> int:
        """Multi-step locality-aware rule (ErasureCodeLrc::create_rule)."""
        if crush.find_rule_by_name(name) >= 0:
            return -17
        root = crush.name_to_item(self.rule_root)
        steps = [RuleStep(CRUSH_RULE_TAKE, root)]
        for step in self.rule_steps:
            domain = crush.type_id(step.type) if step.type else 0
            if step.op == "choose":
                steps.append(RuleStep(CRUSH_RULE_CHOOSE_INDEP, step.n, domain))
            elif step.op == "chooseleaf":
                steps.append(
                    RuleStep(CRUSH_RULE_CHOOSELEAF_INDEP, step.n, domain))
            else:
                raise ErasureCodeError(22, f"unknown crush step op {step.op}")
        steps.append(RuleStep(CRUSH_RULE_EMIT))
        return crush.add_rule(Rule(name, steps, rule_type=3))

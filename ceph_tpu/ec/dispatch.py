"""Shared device-dispatch helpers for the erasure codecs.

One home for the two patterns every codec repeats (flagged by review):
GF matmul routed host-vs-TPU, and the bounded LRU cache keyed by erasure
signature (the ErasureCodeIsaTableCache role).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Hashable, Optional

import numpy as np

from ceph_tpu.common import circuit
from ceph_tpu.ops import gf


def gf_matmul(mat: np.ndarray, data: np.ndarray, use_tpu: bool,
              min_bytes: int = 1, sig: Optional[str] = None,
              use_plan: bool = True,
              family: str = "ec-encode") -> np.ndarray:
    """(R,K) GF(2^8) matrix x (K,S) or (B,K,S) uint8, device-dispatched.

    The device branch routes through the ExecPlan cache (ec/plan.py):
    shapes bucket onto a handful of compiled plans, and the plan
    delegates to the LIVE HEALTHY device mesh (parallel/backend.py)
    — the daemons' EC path and the multi-chip dryrun compile the same
    program; a single chip is the (1,1) mesh, and a chip whose
    ``device:<id>`` breaker is open is simply absent from the next
    mesh build.  `sig` is the codec's plan signature; use_plan=False
    (the --no-plan-cache toggle) dispatches with exact shapes.

    Every device attempt rides the `family` circuit breaker
    (common/circuit.py): while the breaker is open — or when the
    guarded dispatch fails, times out, or exhausts OOM halving — the
    call degrades to the bit-exact numpy host fold below, so callers
    NEVER see a device error from this entry.
    """
    if use_tpu and gf.backend_available() and data.size >= min_bytes:
        if not circuit.degraded(family):
            out = _device_matmul(mat, data, sig, use_plan, family)
            if out is not None:
                return out
        else:
            circuit.breaker(family).note_fallback()
    if data.ndim == 2:
        return gf.gf_matmul_host(mat, data)
    # batched host path: the GF matmul is elementwise across columns, so
    # B stripes fold into ONE wide (K, B*S) region op — per-stripe calls
    # would pay kernel setup B times for tiny regions
    b, k, s = data.shape
    flat = np.ascontiguousarray(np.moveaxis(data, 1, 0)).reshape(k, b * s)
    par = gf.gf_matmul_host(mat, flat)
    return np.moveaxis(par.reshape(-1, b, s), 0, 1)


def _device_matmul(mat: np.ndarray, data: np.ndarray,
                   sig: Optional[str], use_plan: bool,
                   family: str) -> Optional[np.ndarray]:
    """The device tiers in preference order, every dispatch guarded;
    None means 'take the host path'."""
    if use_plan:
        from ceph_tpu.ec import plan

        if plan.enabled():
            out = plan.matmul(mat, data, sig=sig, family=family)
            if out is not None:
                return out
    if circuit.degraded(family):     # the plan attempt may have tripped
        return None
    from ceph_tpu.parallel import backend

    batch = data.shape[0] if data.ndim == 3 else 1
    status, out = circuit.device_call(
        family, backend.matmul, mat, data, batch=batch,
        label="mesh-direct", oom_to_fail=batch <= 1,
        devices=backend.mesh_device_ids() or None)
    if status == "ok" and out is not None:
        return out
    if status == "oom" and batch > 1:
        # np.split hands back views of the same stripes (no byte
        # moves); each half re-dispatches under its own guard
        first_half, second_half = np.split(data, [batch // 2])
        first = _device_matmul(mat, first_half, sig, use_plan, family)
        second = _device_matmul(mat, second_half, sig, use_plan,
                                family)
        if first is not None and second is not None:
            return np.concatenate([first, second], axis=0)
        return None
    if status in ("fail", "timeout", "open", "oom"):
        return None
    # mesh declined the shape (ok, None): the single-device XLA kernel.
    # np.asarray INSIDE the guarded body: the dispatch is async, so a
    # late error/wedge must land under the watchdog, not at the caller
    status, out = circuit.device_call(
        family, lambda: np.asarray(gf.gf_matmul_tpu(mat, data)),
        batch=batch, label="xla-direct", oom_to_fail=True)
    return out if status == "ok" else None


def gf_repair_matmul(mat: np.ndarray, data: np.ndarray,
                     use_tpu: bool = True, min_bytes: int = 1,
                     sig: Optional[str] = None, use_plan: bool = True,
                     family: str = "ec-repair") -> np.ndarray:
    """Repair-kind twin of gf_matmul for the regenerating-code path:
    helper-side projections (1 x alpha) and primary-side
    reconstructions (alpha x d) dispatch through the `repair` plan
    kind (ec/plan.py), where the small per-erasure-pattern matrix is
    a compile-time constant baked into the trace — memoized by codec
    signature + erasure pattern, xsched-compiled when the bit
    expansion wins.  Rides its own `ec-repair` breaker family so a
    repair-path fault never degrades the encode/decode data path;
    while degraded (or when the guarded dispatch fails) the call
    takes the bit-exact numpy host fold below, so callers NEVER see
    a device error from this entry.
    """
    if use_tpu and gf.backend_available() and data.size >= min_bytes:
        if not circuit.degraded(family):
            if use_plan:
                from ceph_tpu.ec import plan

                if plan.enabled():
                    out = plan.repair(mat, data, sig=sig, family=family)
                    if out is not None:
                        return out
        else:
            circuit.breaker(family).note_fallback()
    if data.ndim == 2:
        return gf.gf_matmul_host(mat, data)
    b, k, s = data.shape
    flat = np.ascontiguousarray(np.moveaxis(data, 1, 0)).reshape(k, b * s)
    par = gf.gf_matmul_host(mat, flat)
    return np.moveaxis(par.reshape(-1, b, s), 0, 1)


class LruCache:
    """Tiny bounded LRU (decode tables keyed by erasure signature,
    GF multiply tables, compiled ExecPlans).  Overflow evicts the
    least-recently-used entry only — never the whole store."""

    def __init__(self, cap: int = 256):
        self._store: OrderedDict = OrderedDict()
        self.cap = cap

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._store

    _MISS = object()

    def peek(self, key: Hashable, default=None):
        """Lookup + LRU touch without computing on miss (callers that
        must build outside a lock pair this with put)."""
        hit = self._store.get(key, self._MISS)
        if hit is self._MISS:
            return default
        self._store.move_to_end(key)
        return hit

    def put(self, key: Hashable, value) -> None:
        self._store[key] = value
        self._store.move_to_end(key)
        if len(self._store) > self.cap:
            self._store.popitem(last=False)

    def pop(self, key: Hashable, default=None):
        """Evict one entry (the poisoned-plan quarantine path)."""
        return self._store.pop(key, default)

    def clear(self) -> None:
        self._store.clear()

    def get_or_compute(self, key: Hashable, compute: Callable):
        hit = self._store.get(key, self._MISS)
        if hit is not self._MISS:
            self._store.move_to_end(key)
            return hit
        value = compute()
        self.put(key, value)
        return value


# ---------------------------------------------------------------------------
# Shared decode-rows cache
# ---------------------------------------------------------------------------

# Inverted decode submatrices keyed by (codec signature, survivors,
# erasures) — PROCESS-wide, not per codec instance: pool remounts and
# registry re-resolution build fresh codec objects for identical
# profiles, and a per-instance cache made each of them re-run the
# GF(2) Gaussian elimination for every erasure pattern it had already
# seen.  The signature (xsched.matrix_signature over the generator +
# geometry) makes identical profiles collide on purpose and distinct
# ones never.
_decode_rows = LruCache(cap=512)
_decode_rows_stats = {"hits": 0, "misses": 0}
# decode runs on asyncio.to_thread executor threads (the encode
# service's off-loop workers) as well as the event loop: peek()'s
# get-then-move_to_end is not atomic under concurrent eviction, so
# the process-wide cache takes a lock (the inversion itself runs
# OUTSIDE it — Gaussian elimination can take milliseconds)
_decode_rows_lock = threading.Lock()


def shared_decode_rows(key: Hashable, compute: Callable):
    """Fetch (or invert-and-cache) decode rows for one (codec sig,
    erasure pattern); counters feed decode_rows_stats() so the
    cross-instance reuse is observable."""
    with _decode_rows_lock:
        hit = _decode_rows.peek(key, LruCache._MISS)
        if hit is not LruCache._MISS:
            _decode_rows_stats["hits"] += 1
            return hit
        _decode_rows_stats["misses"] += 1
    value = compute()
    with _decode_rows_lock:
        _decode_rows.put(key, value)
    return value


def decode_rows_stats() -> dict:
    with _decode_rows_lock:
        return {**_decode_rows_stats, "entries": len(_decode_rows)}

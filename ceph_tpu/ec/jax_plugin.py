"""`ec_jax` — the TPU erasure codec (the framework's flagship compute path).

Reference parity: techniques reed_sol_van / reed_sol_r6_op / cauchy_orig /
cauchy_good of the jerasure plugin
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc), plus the
isa plugin's decode strategy — invert the surviving k x k generator submatrix
and LRU-cache decode tables keyed by the erasure signature
(/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:151-311,
ErasureCodeIsaTableCache.cc).

TPU-first design: encode/decode are GF(2) bit-matrix matmuls on the MXU
(ceph_tpu.ops.gf), batched over stripes.  The single-object API matches the
reference interface; the batched API (encode_batch/decode_batch) is what the
object store and benchmarks drive, amortizing host->device transfers over
many stripes per dispatch.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from ceph_tpu.ec import dispatch
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, to_bool, to_int
from ceph_tpu.models import reed_solomon as rs
from ceph_tpu.ops import checksum as cks
from ceph_tpu.ops import gf

LARGEST_VECTOR_WORDSIZE = 16  # layout-parity constant from the reference


class ErasureCodeJax(ErasureCode):
    """GF(2^8) matrix codec executed on TPU (or host numpy fallback)."""

    TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")

    def __init__(self, technique: str = "reed_sol_van") -> None:
        super().__init__()
        if technique not in self.TECHNIQUES:
            raise ErasureCodeError(2, f"unknown technique {technique}")
        self.technique = technique
        self.w = 8
        self.per_chunk_alignment = False
        self.packetsize = 2048
        self.matrix: np.ndarray | None = None
        self._mbits_dev = None
        self._decode_cache = dispatch.LruCache(256)
        self.use_tpu = True
        self.tpu_min_bytes = 1  # kernel engages for everything unless configured
        self.use_plan = True    # route device dispatch through ec/plan.py
        self._plan_sig: str | None = None

    # -- init -------------------------------------------------------------

    def init(self, profile: Dict[str, str]) -> None:
        profile["technique"] = self.technique
        defaults = {"reed_sol_van": ("2", "1"), "reed_sol_r6_op": ("7", "2"),
                    "cauchy_orig": ("7", "3"), "cauchy_good": ("7", "3")}
        dk, dm = defaults[self.technique]
        self.k = to_int("k", profile, dk)
        self.m = to_int("m", profile, dm)
        self.w = to_int("w", profile, "8")
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(22, f"w={self.w} not in {{8, 16, 32}}")
        if self.w != 8 and self.technique != "reed_sol_van":
            # matches the reference: wide words are a reed_sol_van
            # feature; the cauchy/r6 constructions here are w=8
            # (ErasureCodeJerasure.cc:62-78 parses w per technique)
            raise ErasureCodeError(
                22, f"technique {self.technique} supports w=8 only")
        if self.technique == "reed_sol_r6_op" and self.m != 2:
            raise ErasureCodeError(22, "reed_sol_r6_op requires m=2")
        self.per_chunk_alignment = to_bool(
            "jerasure-per-chunk-alignment", profile, "false")
        if self.technique.startswith("cauchy"):
            self.packetsize = to_int("packetsize", profile, "2048")
        self.use_tpu = to_bool("tpu", profile, "true") and gf.backend_available()
        self.tpu_min_bytes = to_int("tpu-min-bytes", profile, "1")
        self.use_plan = to_bool("plan-cache", profile, "true")
        self.sanity_check_k_m(self.k, self.m)
        mapping = profile.get("mapping")
        if mapping and len(mapping) != self.k + self.m:
            raise ErasureCodeError(
                22, f"mapping {mapping} maps {len(mapping)} chunks, expected"
                f" {self.k + self.m}")
        super().init(profile)
        self._prepare()

    def _prepare(self) -> None:
        if self.technique == "reed_sol_van" and self.w != 8:
            from ceph_tpu.models import gf_wide

            # wide-word Vandermonde (GF(2^16)/GF(2^32)); the device
            # layout is w=8-specific, so wide codecs run the host tier
            self.matrix = gf_wide.reed_sol_van_matrix_w(
                self.k, self.m, self.w)
            self.use_tpu = False
            return
        if self.technique == "reed_sol_van":
            self.matrix = rs.reed_sol_van_matrix(self.k, self.m)
        elif self.technique == "reed_sol_r6_op":
            self.matrix = rs.reed_sol_r6_matrix(self.k)
        elif self.technique == "cauchy_orig":
            self.matrix = rs.cauchy_orig_matrix(self.k, self.m)
        else:
            self.matrix = rs.cauchy_good_matrix(self.k, self.m)
        if self.use_tpu:
            import jax.numpy as jnp

            from ceph_tpu.ops import gf_pallas

            # Hot generator matrix: compiles into the specialized
            # unrolled Pallas kernel on first device dispatch.
            gf_pallas.register_matrix(self.matrix)
            self._mbits_dev = jnp.asarray(gf.gf_matrix_to_bits(self.matrix))

    # -- geometry (layout-parity with ErasureCodeJerasure) ----------------

    def get_alignment(self) -> int:
        if self.technique.startswith("cauchy"):
            unit = self.w * self.packetsize * 4
            if unit % LARGEST_VECTOR_WORDSIZE:
                return self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
            return self.k * unit
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, object_size: int) -> int:
        if self.per_chunk_alignment:
            alignment = (self.w * LARGEST_VECTOR_WORDSIZE
                         if not self.technique.startswith("cauchy")
                         else self._cauchy_per_chunk_alignment())
            chunk_size = -(-object_size // self.k)
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        return super().get_chunk_size(object_size)

    def _cauchy_per_chunk_alignment(self) -> int:
        alignment = self.w * self.packetsize
        modulo = alignment % LARGEST_VECTOR_WORDSIZE
        if modulo:
            alignment += LARGEST_VECTOR_WORDSIZE - modulo
        return alignment

    def supports_result_decode(self) -> bool:
        """True when GF-linear compute kernels commute with this
        codec (the coded-compute pushdown gate, ceph_tpu/compute):
        every plain GF(2^8) matrix technique acts POSITION-WISE on
        bytes, so a kernel result vector satisfies the same code
        relation as the shards and decodes through the normal decode
        path at lane width.  Wide-word (w>8) and cauchy variants mix
        across byte/word boundaries or carry per-chunk alignment the
        lane-width synthetic stripe cannot honor; remapped layouts
        (chunk_mapping) are excluded with them — those codecs take
        the full-decode fallback."""
        return (self.matrix is not None and self.w == 8
                and not self.technique.startswith("cauchy")
                and not self.get_chunk_mapping())

    # -- kernels ----------------------------------------------------------

    def plan_signature(self) -> str:
        """Stable-across-processes identity of this codec's generator
        (the ExecPlan cache key prefix; see ec/plan.py)."""
        if self._plan_sig is None:
            from ceph_tpu.ec import plan

            self._plan_sig = plan.codec_signature(
                self.technique, self.k, self.m, self.w, self.matrix)
        return self._plan_sig

    def _matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        """(R,K) GF matrix x (K,S) or (B,K,S) uint8 -> parity, device-dispatched."""
        if self.w != 8:
            return self._matmul_wide(mat, data)
        encode = mat is self.matrix
        sig = self.plan_signature() if encode else None
        return dispatch.gf_matmul(
            mat, data, self.use_tpu, self.tpu_min_bytes, sig=sig,
            use_plan=self.use_plan,
            # the generator matmul is the encode family; everything
            # else (inverted decode rows) is ec-decode — each trips
            # and recovers its own breaker
            family="ec-encode" if encode else "ec-decode")

    def _matmul_wide(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        """Host GF(2^w) matmul for w in {16, 32}: chunks viewed as
        little-endian w-bit words (jerasure's word semantics)."""
        from ceph_tpu.models import gf_wide

        f = gf_wide.Field(self.w)
        batched = data.ndim == 3
        if not batched:
            data = data[None]
        b, kk, s = data.shape
        assert s % (self.w // 8) == 0, (s, self.w)
        words = data.view(f.dtype)
        out = np.zeros((b, mat.shape[0], words.shape[-1]), dtype=f.dtype)
        for j in range(mat.shape[0]):
            for i in range(kk):
                c = int(mat[j, i])
                if c == 0:
                    continue
                if c == 1:
                    out[:, j] ^= words[:, i]
                else:
                    out[:, j] ^= f.mul_vec(c, words[:, i])
        res = out.view(np.uint8).reshape(b, mat.shape[0], s)
        return res if batched else res[0]

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        # frombuffer reads the bytearrays in place (np.stack owns the
        # copy it needs); parity rows land back via their buffer view
        # — the old bytes()/tobytes() round trip re-copied every
        # chunk twice per encode
        data = np.stack([
            np.frombuffer(encoded[self.chunk_index(i)], dtype=np.uint8)
            for i in range(k)])
        parity = np.ascontiguousarray(self._matmul(self.matrix, data))
        for j in range(m):
            encoded[self.chunk_index(k + j)][:] = parity[j].data

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        k, m = self.k, self.m
        # Positions on disk map to logical chunk ids through chunk_mapping;
        # the generator-matrix math lives in logical space.
        erasures = [i for i in range(k + m) if self.chunk_index(i) not in chunks]
        if not erasures:
            return
        have = [i for i in range(k + m) if self.chunk_index(i) in chunks][:k]
        if len(have) < k:
            raise ErasureCodeError(5, "not enough chunks to decode")
        dmat = self._decode_matrix(tuple(have), tuple(erasures))
        src = np.stack([
            np.frombuffer(decoded[self.chunk_index(i)], dtype=np.uint8)
            for i in have])
        out = np.ascontiguousarray(self._matmul(dmat, src))
        for row, e in enumerate(erasures):
            decoded[self.chunk_index(e)][:] = out[row].data

    def _decode_matrix(self, have: tuple, erasures: tuple) -> np.ndarray:
        """LRU-cached decode rows keyed by (have, erasures) — the signature
        cache of ErasureCodeIsaTableCache."""
        if self.w != 8:
            from ceph_tpu.models import gf_wide

            return self._decode_cache.get_or_compute(
                (have, erasures),
                lambda: gf_wide.decode_matrix_w(
                    self.matrix, self.k, list(erasures), list(have),
                    self.w))
        return self._decode_cache.get_or_compute(
            (have, erasures),
            lambda: rs.decode_matrix(self.matrix, self.k,
                                     list(erasures), list(have)))

    # -- batched API (the TPU-native entry points) ------------------------

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(B, k, S) uint8 stripes -> (B, m, S) parity in one device dispatch."""
        assert data.ndim == 3 and data.shape[1] == self.k
        return self._matmul(self.matrix, data)

    def decode_batch(self, have: tuple, erasures: tuple,
                     survivors: np.ndarray) -> np.ndarray:
        """(B, k, S) surviving chunks (rows in `have` order) -> erased chunks."""
        dmat = self._decode_matrix(tuple(have), tuple(erasures))
        return self._matmul(dmat, survivors)

    def encode_many(self, datas: Sequence[np.ndarray]
                    ) -> List[np.ndarray]:
        """Coalesced encode: N pending (k, S_i) stripes -> parities in
        order, folded into ONE batched device dispatch (ec/plan.py's
        StripeCoalescer; ragged widths pad to the common bucket)."""
        if self.w != 8 or not datas:
            return [self._matmul(self.matrix, np.asarray(d, np.uint8))
                    for d in datas]
        from ceph_tpu.ec import plan

        total = sum(int(np.asarray(d).size) for d in datas)
        if self.use_tpu and self.use_plan and plan.enabled() \
                and total >= self.tpu_min_bytes:
            return plan.encode_coalesced(self.matrix, datas,
                                         sig=self.plan_signature())
        return [self._matmul(self.matrix, np.asarray(d, np.uint8))
                for d in datas]

    def encode_many_with_crc(self, arrs: Sequence[np.ndarray],
                             init: int = 0
                             ) -> Optional[List[Tuple[np.ndarray,
                                                      np.ndarray]]]:
        """N pending (B_i, k, S) stripe batches -> [(parity_i, crc_i)]
        in order, folded into ONE fused encode+crc dispatch: same-S
        batches concatenate along the stripe axis (the encode
        service's flush path — many concurrent objects, one plan
        call).  None when the fused plan is unavailable (callers fall
        back per item)."""
        if self.w != 8 or not self.use_tpu or not self.use_plan:
            return None
        from ceph_tpu.ec import plan

        if not plan.enabled():
            return None
        arrs = [np.asarray(a, dtype=np.uint8) for a in arrs]
        if not arrs:
            return []
        s = arrs[0].shape[-1]
        if any(a.ndim != 3 or a.shape[1] != self.k or a.shape[2] != s
               for a in arrs):
            return None
        big = arrs[0] if len(arrs) == 1 else np.concatenate(arrs, axis=0)
        out = plan.encode_with_crc(self.matrix, big,
                                   sig=self.plan_signature())
        if out is None:
            return None
        parity, crcs = out
        if init:
            adv = cks.crc32c_zeros(init & 0xFFFFFFFF, s)
            crcs = crcs ^ np.uint32(adv)
        res: List[Tuple[np.ndarray, np.ndarray]] = []
        off = 0
        for a in arrs:
            b = a.shape[0]
            res.append((parity[off:off + b], crcs[off:off + b]))
            off += b
        return res

    def encode_batch_with_crc(self, data: np.ndarray, init: int = 0
                              ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Fused encode + per-chunk crc32c in one device dispatch:
        (B, k, S) -> (parity (B, m, S), crcs (B, k+m) uint32 seeded
        `init`).  None when the fused plan is unavailable (callers
        fall back to encode + host CRC)."""
        if self.w != 8 or not self.use_tpu or not self.use_plan:
            return None
        from ceph_tpu.ec import plan

        if not plan.enabled():
            return None
        out = plan.encode_with_crc(self.matrix, data,
                                   sig=self.plan_signature())
        if out is None:
            return None
        parity, crcs = out
        if init:
            # crc32c(init, chunk) = crc32c_zeros(init, S) ^ crc32c(0, chunk)
            adv = cks.crc32c_zeros(init & 0xFFFFFFFF, data.shape[-1])
            crcs = crcs ^ np.uint32(adv)
        return parity, crcs

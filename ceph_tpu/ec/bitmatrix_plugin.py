"""Bit-matrix RAID-6 techniques: liberation / blaum_roth / liber8tion.

Reference parity: the jerasure plugin's bitmatrix technique family
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:452
ErasureCodeJerasureLiberation, :476 BlaumRoth, :488-513 Liber8tion)
with the same profile surface (k, m=2, w, packetsize) and the same
parameter adjudication (prime/w constraints, k <= w).  Matrix
constructions live in models/bitmatrix.py (see its docstring for the
published-definition provenance and the liber8tion deviation note).

Execution model: a chunk is w packets of `packetsize` bytes repeated
across the chunk (jerasure_bitmatrix_encode's packet walk); coding
packet r of chunk j is the XOR of the data packets selected by
bitmatrix row j*w + r.  Packet XOR is VPU/host-SIMD-shaped work, not
MXU work — the reference runs these codes on CPU XOR too — so the
execution tier is the COMPILED XOR schedule (ec/xsched.py: Paar CSE
+ scheduling + memoization by codec/submatrix sha256 signature) run
over zero-copy packet views (models/bitmatrix.packet_views) straight
off the chunk buffers; CEPH_TPU_XSCHED=0 pins the naive row-walk
(xsched.naive_xor_matmul — bit-identical).  Decode inverts the
surviving k*w x k*w bit submatrix (models/bitmatrix.decode_bitmatrix)
ONCE per (codec, erasure pattern) PROCESS-wide: the inverted rows
live in ec/dispatch.py's shared signature-keyed cache, so
re-instantiated codecs (pool remounts, registry re-resolution) reuse
them.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Set

import numpy as np

from ceph_tpu.ec import dispatch, xsched
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, to_int
from ceph_tpu.models import bitmatrix as bmx

DEFAULT_PACKETSIZE = 2048


class ErasureCodeJaxBitmatrix(ErasureCode):
    """GF(2) bitmatrix RAID-6 codec (m = 2)."""

    TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")

    def __init__(self, technique: str = "liberation") -> None:
        super().__init__()
        if technique not in self.TECHNIQUES:
            raise ErasureCodeError(2, f"unknown technique {technique}")
        self.technique = technique
        self.w = 7
        self.packetsize = DEFAULT_PACKETSIZE
        self.bitmatrix: np.ndarray | None = None
        self._sig: str | None = None

    def init(self, profile: Dict[str, str]) -> None:
        profile["technique"] = self.technique
        self.k = to_int("k", profile, "2")
        self.m = to_int("m", profile, "2")
        default_w = {"liberation": "7", "blaum_roth": "6",
                     "liber8tion": "8"}[self.technique]
        self.w = to_int("w", profile, default_w)
        self.packetsize = to_int("packetsize", profile,
                                 str(DEFAULT_PACKETSIZE))
        # parameter adjudication mirrors the reference's revert-with-
        # notice behavior (ErasureCodeJerasure.cc:432-513) as hard
        # errors: a silently-adjusted geometry would change placement
        if self.m != 2:
            raise ErasureCodeError(
                22, f"{self.technique}: m={self.m} must be 2")
        if self.technique == "liber8tion" and self.w != 8:
            raise ErasureCodeError(
                22, f"liber8tion: w={self.w} must be 8")
        if self.k > self.w:
            raise ErasureCodeError(
                22, f"{self.technique}: k={self.k} must be <= w={self.w}")
        self.sanity_check_k_m(self.k, self.m)
        mapping = profile.get("mapping")
        if mapping and len(mapping) != self.k + self.m:
            raise ErasureCodeError(
                22, f"mapping {mapping} maps {len(mapping)} chunks,"
                f" expected {self.k + self.m}")
        super().init(profile)
        try:
            if self.technique == "liberation":
                self.bitmatrix = bmx.liberation_bitmatrix(self.k, self.w)
            elif self.technique == "blaum_roth":
                self.bitmatrix = bmx.blaum_roth_bitmatrix(self.k, self.w)
            else:
                self.bitmatrix = bmx.liber8tion_bitmatrix(self.k)
        except ValueError as e:  # prime/bound violations
            raise ErasureCodeError(22, str(e))
        # process-stable codec identity: keys the shared decode-rows
        # cache AND the memoized XOR schedules (the ExecPlan signature
        # discipline — identical profiles share everything)
        self._sig = xsched.matrix_signature(
            self.bitmatrix,
            extra=f"{self.technique}/k{self.k}/w{self.w}")

    # -- geometry ----------------------------------------------------------

    def get_alignment(self) -> int:
        # every chunk must hold whole w-packet blocks
        return self.k * self.w * self.packetsize

    # -- packet math -------------------------------------------------------

    def _packets(self, arrs: np.ndarray) -> np.ndarray:
        """(n, chunk) -> (blocks, n*w, packetsize) packet stacks (the
        naive kill-switch path's layout)."""
        n, chunk = arrs.shape
        blk = self.w * self.packetsize
        assert chunk % blk == 0, (chunk, blk)
        b = chunk // blk
        return np.ascontiguousarray(
            arrs.reshape(n, b, self.w, self.packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(b, n * self.w, self.packetsize))

    def _unpackets(self, pk: np.ndarray, n: int) -> np.ndarray:
        """(blocks, n*w, ps) -> (n, chunk) chunk bytes."""
        b = pk.shape[0]
        return np.ascontiguousarray(
            pk.reshape(b, n, self.w, self.packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(n, b * self.w * self.packetsize))

    def _column_views(self, bufs: List) -> List[np.ndarray]:
        """Chunk buffers (logical order) -> the bitmatrix's input
        columns: column i*w + c is packet c of chunk i, each a
        zero-copy (blocks, packetsize) view over the caller's
        buffer."""
        cols: List[np.ndarray] = []
        for buf in bufs:
            cols.extend(bmx.packet_views(buf, self.w, self.packetsize))
        return cols

    def _pack_arena(self, prog, src_bufs: List) -> np.ndarray:
        """One execution arena for the native tape: ``(n_regions,
        blocks * packetsize)`` with input columns filled from the
        source chunks.  When blocks == 1 a chunk's bytes ARE its w
        input regions back to back, so filling is one flat copy per
        chunk; multi-block chunks take one strided transpose-copy per
        chunk (block-major packets -> packet-major regions)."""
        w, ps = self.w, self.packetsize
        blocks = len(src_bufs[0]) // (w * ps)
        arena = np.empty((prog.n_regions, blocks * ps), np.uint8)
        cols = arena[:prog.n_in]
        if blocks == 1:
            flat = cols.reshape(len(src_bufs), w * ps)
            for i, src in enumerate(src_bufs):
                flat[i] = np.frombuffer(src, np.uint8)
        else:
            grid = cols.reshape(len(src_bufs), w, blocks, ps)
            for i, src in enumerate(src_bufs):
                grid[i] = (np.frombuffer(src, np.uint8)
                           .reshape(blocks, w, ps).transpose(1, 0, 2))
        return arena

    def _unpack_arena(self, prog, arena: np.ndarray,
                      dst_bufs: List) -> None:
        """Write the arena's output regions back into the destination
        chunk buffers (the inverse layout of `_pack_arena`)."""
        w, ps = self.w, self.packetsize
        blocks = arena.shape[1] // ps
        rows = arena[prog.out_base:]
        if blocks == 1:
            flat = rows.reshape(len(dst_bufs), w * ps)
            for j, dst in enumerate(dst_bufs):
                np.frombuffer(dst, np.uint8)[...] = flat[j]
        else:
            grid = rows.reshape(len(dst_bufs), w, blocks, ps)
            for j, dst in enumerate(dst_bufs):
                (np.frombuffer(dst, np.uint8).reshape(blocks, w, ps)
                 )[...] = grid[j].transpose(1, 0, 2)

    def _run(self, rows: np.ndarray, sched_sig: str,
             src_bufs: List, dst_bufs: List) -> None:
        """Execute `rows` over the source chunks into the destination
        chunks: the compiled XOR schedule by default — lowered to ONE
        fused native tape run over a packed chunk arena when the
        native executor is built and enabled
        (CEPH_TPU_NATIVE_XSCHED=0 falls back to the per-op host tier
        over zero-copy packet views, bit-identical) — and the naive
        row-walk under the kill switch (the bit-exactness oracle) or
        when the matrix is too dense to compile on the serving path
        (host_compile_allowed — cached schedules aside, the
        pure-Python CSE must not stall the event loop on a
        pathological geometry)."""
        if xsched.enabled() and xsched.host_compile_allowed(rows):
            sched = xsched.compile_matrix(rows, sig=sched_sig)
            if xsched.native_available():
                prog = xsched.lower_program(sched)
                arena = self._pack_arena(prog, src_bufs)
                xsched.execute_native(prog, arena)
                self._unpack_arena(prog, arena, dst_bufs)
                return
            outs = self._column_views(dst_bufs)
            xsched.execute_host(sched, self._column_views(src_bufs),
                                outs)
            return
        data = np.stack([np.frombuffer(b, dtype=np.uint8)
                         for b in src_bufs])
        out = self._unpackets(
            xsched.naive_xor_matmul(rows, self._packets(data)),
            len(dst_bufs))
        for j, dst in enumerate(dst_bufs):
            dst[:] = out[j].data

    # -- interface kernels -------------------------------------------------

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        # buffers are keyed by on-disk POSITION (chunk_index); the
        # bitmatrix math lives in logical chunk space.  Packet views
        # read the data bytearrays in place and coding packets are
        # written straight into the output bytearrays' views — the
        # schedule path stacks/copies nothing
        self._run(self.bitmatrix, self._sig,
                  [encoded[self.chunk_index(i)] for i in range(self.k)],
                  [encoded[self.chunk_index(self.k + j)]
                   for j in range(self.m)])

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        n = self.k + self.m
        erasures = tuple(i for i in range(n)
                         if self.chunk_index(i) not in chunks)
        if not erasures:
            return
        have = tuple(i for i in range(n)
                     if self.chunk_index(i) in chunks)[:self.k]
        if len(have) < self.k:
            raise ErasureCodeError(5, "not enough chunks to decode")
        # the inverted submatrix is shared PROCESS-wide by codec
        # signature (ec/dispatch.py): a remounted pool's fresh codec
        # instance reuses this instance's inversions, and the decode
        # schedule below is memoized under the same key discipline
        key = (self._sig, have, erasures)
        rows = dispatch.shared_decode_rows(
            key,
            lambda: bmx.decode_bitmatrix(self.bitmatrix, self.k,
                                         self.w, have, erasures))
        self._run(rows, f"{self._sig}/d{have}/{erasures}",
                  [decoded[self.chunk_index(i)] for i in have],
                  [decoded[self.chunk_index(e)] for e in erasures])

"""Bit-matrix RAID-6 techniques: liberation / blaum_roth / liber8tion.

Reference parity: the jerasure plugin's bitmatrix technique family
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:452
ErasureCodeJerasureLiberation, :476 BlaumRoth, :488-513 Liber8tion)
with the same profile surface (k, m=2, w, packetsize) and the same
parameter adjudication (prime/w constraints, k <= w).  Matrix
constructions live in models/bitmatrix.py (see its docstring for the
published-definition provenance and the liber8tion deviation note).

Execution model: a chunk is w packets of `packetsize` bytes repeated
across the chunk (jerasure_bitmatrix_encode's packet walk); coding
packet r of chunk j is the XOR of the data packets selected by
bitmatrix row j*w + r.  Packet XOR is VPU/host-SIMD-shaped work, not
MXU work — the reference runs these codes on CPU XOR too — so the
execution tier is numpy bitwise-XOR over packet views (the native
region-xor underneath numpy's core).  Decode inverts the surviving
k*w x k*w bit submatrix (models/bitmatrix.decode_bitmatrix), the
isa-style signature-keyed cache holding the result.
"""

from __future__ import annotations

from typing import Dict, Mapping, Set

import numpy as np

from ceph_tpu.ec import dispatch
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError, to_int
from ceph_tpu.models import bitmatrix as bmx

DEFAULT_PACKETSIZE = 2048


class ErasureCodeJaxBitmatrix(ErasureCode):
    """GF(2) bitmatrix RAID-6 codec (m = 2)."""

    TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")

    def __init__(self, technique: str = "liberation") -> None:
        super().__init__()
        if technique not in self.TECHNIQUES:
            raise ErasureCodeError(2, f"unknown technique {technique}")
        self.technique = technique
        self.w = 7
        self.packetsize = DEFAULT_PACKETSIZE
        self.bitmatrix: np.ndarray | None = None
        self._decode_cache = dispatch.LruCache(256)

    def init(self, profile: Dict[str, str]) -> None:
        profile["technique"] = self.technique
        self.k = to_int("k", profile, "2")
        self.m = to_int("m", profile, "2")
        default_w = {"liberation": "7", "blaum_roth": "6",
                     "liber8tion": "8"}[self.technique]
        self.w = to_int("w", profile, default_w)
        self.packetsize = to_int("packetsize", profile,
                                 str(DEFAULT_PACKETSIZE))
        # parameter adjudication mirrors the reference's revert-with-
        # notice behavior (ErasureCodeJerasure.cc:432-513) as hard
        # errors: a silently-adjusted geometry would change placement
        if self.m != 2:
            raise ErasureCodeError(
                22, f"{self.technique}: m={self.m} must be 2")
        if self.technique == "liber8tion" and self.w != 8:
            raise ErasureCodeError(
                22, f"liber8tion: w={self.w} must be 8")
        if self.k > self.w:
            raise ErasureCodeError(
                22, f"{self.technique}: k={self.k} must be <= w={self.w}")
        self.sanity_check_k_m(self.k, self.m)
        mapping = profile.get("mapping")
        if mapping and len(mapping) != self.k + self.m:
            raise ErasureCodeError(
                22, f"mapping {mapping} maps {len(mapping)} chunks,"
                f" expected {self.k + self.m}")
        super().init(profile)
        try:
            if self.technique == "liberation":
                self.bitmatrix = bmx.liberation_bitmatrix(self.k, self.w)
            elif self.technique == "blaum_roth":
                self.bitmatrix = bmx.blaum_roth_bitmatrix(self.k, self.w)
            else:
                self.bitmatrix = bmx.liber8tion_bitmatrix(self.k)
        except ValueError as e:  # prime/bound violations
            raise ErasureCodeError(22, str(e))

    # -- geometry ----------------------------------------------------------

    def get_alignment(self) -> int:
        # every chunk must hold whole w-packet blocks
        return self.k * self.w * self.packetsize

    # -- packet math -------------------------------------------------------

    def _packets(self, arrs: np.ndarray) -> np.ndarray:
        """(n, chunk) -> (blocks, n*w, packetsize) packet stacks."""
        n, chunk = arrs.shape
        blk = self.w * self.packetsize
        assert chunk % blk == 0, (chunk, blk)
        b = chunk // blk
        return np.ascontiguousarray(
            arrs.reshape(n, b, self.w, self.packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(b, n * self.w, self.packetsize))

    @staticmethod
    def _xor_matmul(rows: np.ndarray, packets: np.ndarray) -> np.ndarray:
        """(R, C) 0/1 x (B, C, ps) byte packets -> (B, R, ps) XORs."""
        b, _c, ps = packets.shape
        out = np.zeros((b, rows.shape[0], ps), dtype=np.uint8)
        for r in range(rows.shape[0]):
            idx = np.flatnonzero(rows[r])
            if idx.size:
                out[:, r] = np.bitwise_xor.reduce(
                    packets[:, idx, :], axis=1)
        return out

    def _unpackets(self, pk: np.ndarray, n: int) -> np.ndarray:
        """(blocks, n*w, ps) -> (n, chunk) chunk bytes."""
        b = pk.shape[0]
        return np.ascontiguousarray(
            pk.reshape(b, n, self.w, self.packetsize)
            .transpose(1, 0, 2, 3)
            .reshape(n, b * self.w * self.packetsize))

    # -- interface kernels -------------------------------------------------

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, bytearray]) -> None:
        # buffers are keyed by on-disk POSITION (chunk_index); the
        # bitmatrix math lives in logical chunk space.  frombuffer
        # reads in place and rows land back as buffer views (the
        # bytes()/tobytes() round trip was two extra whole-chunk
        # copies per encode)
        data = np.stack([
            np.frombuffer(encoded[self.chunk_index(i)],
                          dtype=np.uint8)
            for i in range(self.k)])
        packets = self._packets(data)
        coding = self._xor_matmul(self.bitmatrix, packets)
        out = np.ascontiguousarray(self._unpackets(coding, self.m))
        for j in range(self.m):
            encoded[self.chunk_index(self.k + j)][:] = out[j].data

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Mapping[int, bytes],
                      decoded: Dict[int, bytearray]) -> None:
        n = self.k + self.m
        erasures = tuple(i for i in range(n)
                         if self.chunk_index(i) not in chunks)
        if not erasures:
            return
        have = tuple(i for i in range(n)
                     if self.chunk_index(i) in chunks)[:self.k]
        if len(have) < self.k:
            raise ErasureCodeError(5, "not enough chunks to decode")
        rows = self._decode_cache.get_or_compute(
            (have, erasures),
            lambda: bmx.decode_bitmatrix(self.bitmatrix, self.k,
                                         self.w, have, erasures))
        survivors = np.stack([
            np.frombuffer(decoded[self.chunk_index(i)],
                          dtype=np.uint8)
            for i in have])
        packets = self._packets(survivors)
        rec = self._xor_matmul(rows, packets)
        out = np.ascontiguousarray(self._unpackets(rec, len(erasures)))
        for row, e in enumerate(erasures):
            decoded[self.chunk_index(e)][:] = out[row].data

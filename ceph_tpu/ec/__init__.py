"""Erasure-code framework: interface, codecs, plugin registry."""

from ceph_tpu.ec.interface import ErasureCode, ErasureCodeProfile  # noqa: F401
from ceph_tpu.ec.registry import ErasureCodePluginRegistry  # noqa: F401

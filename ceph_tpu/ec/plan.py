"""ExecPlan cache: the compile-once, dispatch-few EC device path.

Every encode/decode request used to walk ec/dispatch.gf_matmul ->
jax.jit with its *exact* array shapes, so each new (k, m, chunk_bytes,
batch) combination paid a full XLA retrace, small stripes dispatched
one at a time, and parity + hinfo CRC were separate device round
trips.  The XOR-EC literature puts most of the win in this regime in
the scheduling/fusion around the kernel, not the kernel itself
(arXiv:2108.02692), and batched distributed-matmul work argues for
folding many small products into few large ones (arXiv:1804.10331) —
exactly the shape of the many-small-stripes OSD workload.  This module
is that layer:

* **ExecPlan cache** — compiled callables keyed by (codec signature,
  kind, bucketed shape).  A plan is built once (the retrace) and then
  served from the LRU for every request that lands in the same bucket.
* **Shape bucketing** — chunk_bytes rounds up to quarter-octave
  buckets (the next {4,5,6,7}/4 * 2^e multiple, >= 64) and the stripe
  batch to power-of-two buckets; inputs are zero-padded up and outputs
  sliced back down.  Zero columns produce zero parity columns and
  padded stripes are dropped, so padding is invisible to callers while
  real traffic collapses onto a handful of plans.
* **Stripe coalescing** — `StripeCoalescer` / `encode_coalesced` fold
  N pending same-profile (K, S_i) encodes into ONE batched (B, K, S)
  device call: the device-side twin of the host-path fold in
  ec/dispatch.gf_matmul.
* **Buffer donation** — on TPU the padded input buffer (which this
  module itself creates, so no caller-visible aliasing) is donated to
  the XLA executable; callers that relinquish a device array can opt
  in with donate=True.  Donation is disabled off-TPU where XLA would
  warn and ignore it.
* **Fused encode + crc32c** — `encode_with_crc` returns parity AND the
  per-chunk (zero-seeded) hinfo crc32c from one dispatch instead of
  two (ECUtil::HashInfo's ledger rides the encode).
* **Mesh-sharded plans** — batches past the mesh gates
  (`CEPH_TPU_MESH_MIN_STRIPES` stripes, `CEPH_TPU_MESH_MIN_BYTES`
  bytes, >= 2 healthy chips) compile onto the LIVE HEALTHY device
  mesh instead of one chip: the plan key carries the device-set
  signature, the stripe batch shards data-parallel over the mesh
  ("stripe" -> dp; "shard" and "byte" stay within-chip — the logical
  axis rules in parallel/striped.py), inputs are device_put
  pre-sharded (SNIPPETS [3]) and parity + fused CRC never re-land on
  host between stages.  A failed mesh dispatch probes each
  participating chip individually (common/circuit.py ``device:<id>``
  breakers): a sick chip trips ITS breaker, the family verdict is
  absolved, and the dispatch re-plans on the surviving set — the mesh
  shrinks, the batch never degrades to host because one chip died.
  Kill switch CEPH_TPU_MESH=0 (bit-identical single-device plans).
* **Observability** — `stats()` exposes hit/miss/retrace counters and
  per-plan dispatch counts/timings (plus the mesh section: healthy
  set, dispatches, shrinks); bench.py and the erasure-code benchmark
  CLI surface them.

Direct `jax.jit` on shape-polymorphic EC entry points is flagged by
the `jit-bypass-plan` static-analysis rule; route new compiles through
`tracked_jit` (or a plan kind) so they stay observable and cached.
"""

from __future__ import annotations

import os

from ceph_tpu.common import flags
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.common import circuit, tracing
from ceph_tpu.ec import xsched
from ceph_tpu.ec.dispatch import LruCache
from ceph_tpu.ec.xsched import matrix_signature
from ceph_tpu.ops import checksum as cks
from ceph_tpu.ops import gf

try:  # plan building needs jax; the module stays importable without it
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

__all__ = [
    "bucket_batch", "bucket_bytes", "clear", "codec_signature",
    "compute_eval", "device_platform", "enabled", "encode",
    "encode_coalesced", "encode_with_crc", "matmul",
    "matrix_signature", "mesh_enabled", "mesh_dispatches",
    "mesh_info", "plan_key", "quarantine_info", "reset_stats",
    "set_enabled", "stats", "StripeCoalescer", "tracked_jit",
    "xor_sched_direct",
]

# ---------------------------------------------------------------------------
# State: the plan cache and its counters
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plans = LruCache(cap=128)
_mbits_cache = LruCache(cap=64)      # matrix signature -> device bit matrix
_counters: Dict[str, int] = {"hits": 0, "misses": 0, "retraces": 0,
                             "dispatches": 0, "host_fallbacks": 0,
                             "oom_splits": 0, "quarantines": 0,
                             "mesh_dispatches": 0, "mesh_rows": 0,
                             "mesh_shrinks": 0, "mesh_probes": 0,
                             "host_retirements": 0}
_per_plan: Dict[str, Dict[str, float]] = {}
_enabled = flags.enabled("CEPH_TPU_PLAN_CACHE")
# poisoned-plan quarantine: a compiled callable that keeps failing is
# evicted and its key blacklisted for a TTL (a single bad compile must
# not re-trip the breaker forever while healthy plans keep serving)
_quarantine: Dict[tuple, float] = {}         # key -> expiry (monotonic)
_plan_failures: Dict[tuple, int] = {}        # key -> consecutive fails


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the plan cache on/off (the CLI --no-plan-cache toggle);
    returns the previous state so callers can restore it."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def stats() -> dict:
    """Snapshot of plan-cache observability counters.

    hits/misses count plan-cache lookups; retraces counts actual XLA
    traces (each is one compile); per_plan maps plan labels to
    dispatch counts and cumulative dispatch seconds (host-side
    dispatch time — device completion is asynchronous).
    """
    with _lock:
        out = {
            **_counters,
            "plans": len(_plans),
            "quarantined_plans": len(_quarantine),
            "enabled": _enabled,
            "per_plan": {k: dict(v) for k, v in _per_plan.items()},
        }
    # breaker states + trip/probe/fallback counters ride the same
    # snapshot (the device_health admin command and bench read this)
    out["device_health"] = circuit.stats_all()
    # mesh policy + live healthy set (outside the lock: mesh_info
    # takes it itself)
    out["mesh"] = mesh_info()
    # the codec-compiler section (ec/xsched.py): schedules compiled,
    # memo hits, xors_naive vs xors_scheduled.  Its cache is keyed by
    # matrix signature, NOT plan key — plan rebuilds (mesh shrink,
    # quarantine, clear) never cost a recompilation
    out["xsched"] = xsched.stats()
    return out


def reset_stats() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0
        _per_plan.clear()


def clear() -> None:
    """Drop every cached plan (tests; production never needs this)."""
    with _lock:
        _plans.clear()
        _mbits_cache.clear()
        _quarantine.clear()
        _plan_failures.clear()


def _note_retrace(label: str) -> None:
    # called from inside traced wrappers: runs once per XLA trace
    with _lock:
        _counters["retraces"] += 1
        entry = _per_plan.setdefault(
            label, {"dispatches": 0, "seconds": 0.0, "retraces": 0})
        entry["retraces"] += 1


def _note_dispatch(label: str, seconds: float) -> None:
    with _lock:
        _counters["dispatches"] += 1
        entry = _per_plan.setdefault(
            label, {"dispatches": 0, "seconds": 0.0, "retraces": 0})
        entry["dispatches"] += 1
        entry["seconds"] += seconds


def tracked_jit(label: str, fn: Callable, **jit_kwargs):
    """jax.jit with plan-cache observability: the wrapper body runs at
    trace time only, so the retrace counter increments exactly once
    per XLA compile.  All EC-path compiles must route through here (or
    a plan kind) — the jit-bypass-plan lint rule enforces it."""

    def traced(*args, **kwargs):
        _note_retrace(label)
        return fn(*args, **kwargs)

    traced.__name__ = getattr(fn, "__name__", label)
    return jax.jit(traced, **jit_kwargs)


# ---------------------------------------------------------------------------
# Bucketing policy
# ---------------------------------------------------------------------------

_MIN_BYTES_BUCKET = 64


def _round_up_quarter_octave(n: int) -> int:
    """Smallest value >= n of the form q * 2^(e-3), q in {5,6,7,8}:
    four buckets per octave, worst-case pad < 25%."""
    if n <= 4:
        return max(n, 1)
    e = (n - 1).bit_length()          # n in (2^(e-1), 2^e]
    step = 1 << max(e - 3, 0)
    return -(-n // step) * step


def bucket_bytes(s: int) -> int:
    """Bucket for the chunk-byte axis: quarter-octave, floor 64 (so
    every bucket is a multiple of 16 — divisible by the mesh sp axis
    and the 4-byte word layout)."""
    return _round_up_quarter_octave(max(int(s), _MIN_BYTES_BUCKET))


def bucket_batch(b: int) -> int:
    """Bucket for the stripe-batch axis: next power of two up to 512
    (ragged arrival batches collapse onto log-many plans), then the
    next multiple of 128 — a big one-shot object must not pad, encode
    and CRC up to 2x its stripes just to hit a power of two (waste is
    bounded < 25% above the cap, and batches that large amortize a
    compile anyway)."""
    b = max(int(b), 1)
    if b <= 512:
        return 1 << (b - 1).bit_length()
    return -(-b // 128) * 128


# ---------------------------------------------------------------------------
# Signatures and keys (stable across processes: plain ints + sha256 hex)
# ---------------------------------------------------------------------------


# matrix_signature is defined in ec/xsched.py (re-exported here
# unchanged): compiled XOR schedules and ExecPlans share ONE sha256
# identity per matrix, so a codec's signature keys both caches.


def codec_signature(technique: str, k: int, m: int, w: int,
                    matrix: np.ndarray) -> str:
    """The ErasureCodeIsaTableCache-style codec signature, hashed so
    it is stable across processes and restarts."""
    return matrix_signature(matrix, extra=f"{technique}/k{k}/m{m}/w{w}")


def plan_key(sig: str, kind: str, rows: int, k: int,
             batch: int, chunk_bytes: int,
             donate: bool = False,
             mesh: Tuple[int, ...] = (),
             proc: tuple = ()) -> tuple:
    """Cache key: (codec signature, kind, bucketed shape, mesh,
    process topology).  Pure strings/ints/bools — identical across
    processes for identical profiles (asserted by the key-stability
    test).  `mesh` is the participating device-id set for a
    mesh-sharded plan (a compiled executable binds its devices, so a
    plan built for a set containing a now-dead chip must miss); the
    batch bucket rounds up to a multiple of the mesh size so every
    chip gets whole stripes.  `proc` is the process topology
    (multihost.topology_signature(): process count + per-process
    device-set signature) so plans from different CLUSTER shapes —
    the same 8 chips as 1x8 vs 2x4 — never collide; () is the
    trivial single-host shape, keeping single-process keys
    bit-identical to the pre-multihost form."""
    bb = bucket_batch(batch)
    if mesh:
        bb = -(-bb // len(mesh)) * len(mesh)
    return (sig, kind, int(rows), int(k), bb,
            bucket_bytes(chunk_bytes) if kind not in
            ("encode_crc", "mesh_encode_crc")
            else int(chunk_bytes), bool(donate),
            tuple(int(d) for d in mesh), tuple(proc))


def _label(key: tuple) -> str:
    sig, kind, rows, k, bb, bs, don, mesh, proc = key
    return f"{kind}[{sig}] r{rows}k{k} B{bb} S{bs}" + \
        ("+don" if don else "") + \
        (f"+mesh{len(mesh)}" if mesh else "") + \
        (f"+hosts{proc[0]}" if proc else "")


# ---------------------------------------------------------------------------
# ExecPlan
# ---------------------------------------------------------------------------


class ExecPlan:
    """One compiled dispatch unit: a callable plus its dispatch stats.

    Mesh plans carry `sharding` (a NamedSharding over their device
    set) and `devices` (the participating chip ids, the device_call
    attribution set); single-device plans leave both None/()."""

    __slots__ = ("key", "label", "fn", "executor", "sharding",
                 "devices")

    def __init__(self, key: tuple, fn: Callable, executor: str,
                 sharding=None, devices: Tuple[int, ...] = ()):
        self.key = key
        self.label = _label(key)
        self.fn = fn
        self.executor = executor
        self.sharding = sharding
        self.devices = devices

    def __call__(self, *args):
        t0 = time.perf_counter()
        out = self.fn(*args)
        _note_dispatch(self.label, time.perf_counter() - t0)
        return out


def _get_plan(key: tuple, build: Callable[[], ExecPlan]) -> ExecPlan:
    with _lock:
        hit = _plans.peek(key)
        if hit is not None:
            _counters["hits"] += 1
            return hit
        _counters["misses"] += 1
    plan = build()  # compile outside the lock (can take seconds)
    with _lock:
        _plans.put(key, plan)
    return plan


# ---------------------------------------------------------------------------
# Dispatch guard: breaker + watchdog + OOM splitting + plan quarantine
# ---------------------------------------------------------------------------


def _quarantine_ttl() -> float:
    try:
        return flags.flag_float("CEPH_TPU_PLAN_QUARANTINE_S")
    except ValueError:
        return 30.0


def _plan_fail_limit() -> int:
    try:
        return flags.flag_int("CEPH_TPU_PLAN_FAIL_LIMIT")
    except ValueError:
        return 3


def _quarantined(key: tuple) -> bool:
    """True while a poisoned plan key is blacklisted (callers take the
    host path without rebuilding the callable); an expired entry is
    released so the next request recompiles fresh."""
    with _lock:
        expiry = _quarantine.get(key)
        if expiry is None:
            return False
        if time.monotonic() >= expiry:
            del _quarantine[key]
            _plan_failures.pop(key, None)
            return False
        return True


def _note_plan_failure(key: tuple) -> None:
    """One more dispatch failure for this compiled callable; at the
    limit the plan is evicted from the cache and its key quarantined
    for the TTL (poisoned-plan quarantine)."""
    with _lock:
        n = _plan_failures.get(key, 0) + 1
        _plan_failures[key] = n
        if n >= _plan_fail_limit():
            _plans.pop(key, None)
            _quarantine[key] = time.monotonic() + _quarantine_ttl()
            _plan_failures.pop(key, None)
            _counters["quarantines"] += 1
            tracing.event(f"plan quarantined {_label(key)}")


def quarantine_info() -> dict:
    """Admin view of the poisoned-plan blacklist."""
    now = time.monotonic()
    with _lock:
        return {
            "ttl_s": _quarantine_ttl(),
            "fail_limit": _plan_fail_limit(),
            "entries": [
                {"plan": _label(k),
                 "expires_in_s": round(max(exp - now, 0.0), 3)}
                for k, exp in _quarantine.items()],
        }


def _materialize(out):
    """Force async XLA results to completion INSIDE the guarded body:
    jax dispatch returns placeholder arrays almost immediately, so a
    late runtime error (or a device that wedges mid-execution) would
    otherwise surface at the CALLER's np.asarray — outside the
    watchdog and the breaker's accounting."""
    if out is None:
        return None
    if isinstance(out, tuple):
        return tuple(_materialize(o) for o in out)
    return np.asarray(out)


def _guarded(family: str, key: tuple, plan: ExecPlan, args: tuple,
             batch: int, defer_verdict: bool = False
             ) -> Tuple[str, Optional[object]]:
    """One plan dispatch through the device_call choke point.  Returns
    ("ok", out), ("oom", None) — caller halves the batch — or
    ("fail", None) after recording breaker/quarantine state; callers
    translate "fail" into the bit-exact host path (return None).

    Mesh plans pass defer_verdict=True: a failure there is NOT yet a
    plan failure or a host fallback — the mesh layer first probes the
    participating chips and either shrinks the mesh (chip's fault,
    plan is fine) or falls through to the single-device plan (which
    owns its own accounting)."""

    def run():
        return _materialize(plan(*args))

    status, out = circuit.device_call(
        family, run, batch=batch, label=plan.label,
        oom_to_fail=batch <= 1, devices=plan.devices or None)
    if status == "ok":
        return "ok", out
    if status == "oom":
        with _lock:
            _counters["oom_splits"] += 1
        tracing.event(f"plan oom halving {plan.label}")
        return "oom", None
    if defer_verdict:
        # raw status up: "open" means no dispatch happened (nothing
        # to probe), "fail"/"timeout" mean the mesh layer attributes
        return status, None
    if status in ("fail", "timeout"):
        _note_plan_failure(key)
    with _lock:
        _counters["host_fallbacks"] += 1
    tracing.event(f"plan host fallback {plan.label}")
    return "fail", None


def device_platform() -> Optional[str]:
    """The jax backend platform ('tpu', 'cpu', ...), None when no
    backend initializes (callers gate device-only policies on this)."""
    if not (HAVE_JAX and gf.backend_available()):
        return None
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return None


def _donation_usable() -> bool:
    # off-TPU XLA ignores donation with a warning; don't ask for it
    return device_platform() == "tpu"


def _mbits_for(matrix: np.ndarray):
    # keyed by matrix CONTENT, never by the caller's sig: a sig only
    # buys cache locality, correctness must not depend on callers
    # keeping it matrix-unique.  matrix_signature hashes the buffer
    # in place — the old (shape, tobytes()) key materialized a copy
    # of the matrix on every encode dispatch
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _mbits_cache.get_or_compute(
        matrix_signature(m),
        lambda: jnp.asarray(gf.gf_matrix_to_bits(m)))


def _pad_batch(arr: np.ndarray, bb: int, bs: int) -> np.ndarray:
    b, k, s = arr.shape
    if b == bb and s == bs:
        return arr
    return np.pad(arr, ((0, bb - b), (0, 0), (0, bs - s)))


# ---------------------------------------------------------------------------
# Mesh policy: when a batch rides the multi-chip mesh, and over which
# surviving devices
# ---------------------------------------------------------------------------


def mesh_enabled() -> bool:
    """Multi-chip mesh dispatch kill switch (CEPH_TPU_MESH=0 pins
    every plan to a single device — bit-identical output)."""
    return flags.enabled("CEPH_TPU_MESH")


def _mesh_min_bytes() -> int:
    """Batch-size floor (total data bytes) below which the mesh is
    not worth the fan-out; one chip's plan serves.  Default 1 MiB —
    the same altitude as the fused-CRC floor."""
    try:
        return flags.flag_int("CEPH_TPU_MESH_MIN_BYTES")
    except ValueError:
        return 1 << 20


def _mesh_min_stripes() -> int:
    try:
        return flags.flag_int("CEPH_TPU_MESH_MIN_STRIPES")
    except ValueError:
        return 2


def _mesh_max_devices() -> int:
    """0 = no cap; the bench mesh sweep sets this to measure 1, 2,
    4, 8-chip legs of the SAME workload."""
    try:
        return flags.flag_int("CEPH_TPU_MESH_MAX_DEVICES")
    except ValueError:
        return 0


def _topology() -> tuple:
    """The process-topology plan-key element (multihost seam); () in
    every single-host shape."""
    try:
        from ceph_tpu.parallel import multihost

        return multihost.topology_signature()
    except Exception:  # pragma: no cover - topology layer unavailable
        return ()


def _healthy_jax_devices() -> list:
    """The live healthy device set a mesh plan may bind: every chip
    minus per-chip breaker holdouts minus retired hosts' chips
    (device_degraded consults both), and — in a real multi-process
    group — restricted to the MEMBERSHIP-AGREED set
    (multihost.agreed_healthy: each process publishes its local
    observations through the coordinator KV store; a dead host reads
    as a timeout and is retired, never waited on in a collective), so
    every surviving process derives the same mesh."""
    try:
        devs = list(jax.devices())
    except Exception:
        return []
    healthy = [d for d in devs if not circuit.device_degraded(d.id)]
    try:
        from ceph_tpu.parallel import multihost
    except Exception:  # pragma: no cover - topology tier unavailable
        return healthy
    if not multihost.is_multiprocess():
        return healthy
    try:
        agreed = set(multihost.agreed_healthy(
            [d.id for d in healthy]))
    except Exception:  # pragma: no cover - agreement unavailable
        # the coordinator is unreachable: this process cannot know
        # the group view, and proceeding on its LOCAL view while
        # peers hold the agreed one builds divergent meshes (a
        # cross-process wedge).  Decline the mesh — the caller falls
        # back to the single-device plan and peers retire this
        # process by timeout.
        return []
    return [d for d in healthy if d.id in agreed]


def _mesh_devices(batch: int, nbytes: int) -> Optional[tuple]:
    """The device set a (batch, nbytes) dispatch should shard over,
    or None for the single-device plan: mesh off / too small a batch
    / fewer than two healthy chips.  At most one chip per stripe —
    padding a 3-stripe batch onto 8 chips would compute more zeros
    than data."""
    if not (mesh_enabled() and HAVE_JAX):
        return None
    if batch < _mesh_min_stripes() or nbytes < _mesh_min_bytes():
        return None
    healthy = _healthy_jax_devices()
    cap = _mesh_max_devices()
    if cap:
        healthy = healthy[:cap]
    if len(healthy) < 2:
        return None
    return tuple(healthy[:min(len(healthy), batch)])


def _probe_timeout() -> float:
    try:
        return flags.flag_float("CEPH_TPU_MESH_PROBE_TIMEOUT_S")
    except ValueError:
        return 20.0


def _probe_devices(device_ids: Sequence[int]) -> list:
    """Attribute a failed mesh dispatch: a trivial dispatch PINNED to
    each participating chip, guarded by that chip's own
    ``device:<id>`` breaker (threshold 1 — the probe targeted the
    chip, a failure is decisive and trips it; the sick-device
    injection seam fires here too).  Returns the ids that failed
    their probe."""
    dev_by_id = {d.id: d for d in (jax.devices() if HAVE_JAX else [])}
    sick = []
    for did in device_ids:
        dev = dev_by_id.get(did)
        if dev is None:
            sick.append(did)
            continue

        def probe(d=dev):
            x = jax.device_put(np.arange(8, dtype=np.uint8), d)
            return np.asarray(x + 1)

        status, _ = circuit.device_call(
            f"{circuit.DEVICE_FAMILY_PREFIX}{did}", probe, batch=1,
            label=f"mesh-probe:device{did}", devices=(did,),
            timeout=_probe_timeout())
        with _lock:
            _counters["mesh_probes"] += 1
        if status not in ("ok", "benign", "oom"):
            sick.append(did)
    return sick


def _host_aware() -> bool:
    """True when the topology spans more than one host failure domain
    (a real multi-process group, or the emulated in-process
    CEPH_TPU_MULTIHOST_HOSTS partition) — host-level attribution only
    makes sense then; single-host keeps the PR-9 per-chip path
    bit-identically."""
    try:
        from ceph_tpu.parallel import multihost

        return multihost.host_count() > 1
    except Exception:  # pragma: no cover
        return False


def _attribute_failure(device_ids: Sequence[int]
                       ) -> Tuple[List[int], List[int]]:
    """Host-aware attribution of a failed mesh dispatch: probe each
    LOCALLY-addressable participant verdict-free (circuit.probe_raw —
    watchdog + injection seam, NO breaker recording), then aggregate
    BEFORE any verdict lands:

    * a host ALL of whose participating chips failed is retired as
      ONE ``host:<id>`` breaker event — its chips' own breakers never
      fire (no N-chip breaker storm);
    * chips failing inside a still-alive host trip their own
      threshold-1 breakers (the PR-9 sick-chip semantics);
    * REMOTE hosts (a real multi-process group) are never probed from
      here — the collective-safe membership agreement owns their
      verdict: the memo is invalidated and the next healthy-set
      derivation re-agrees, retiring hosts that no longer answer.

    Returns (retired hosts, sick devices)."""
    from ceph_tpu.parallel import multihost

    # snapshot hosts ALREADY degraded before this round: a host in
    # backoff from an earlier retirement must not be re-reported as
    # this failure's attribution (that would absolve the family
    # breaker forever and spin the shrink loop on an unchanged set)
    pre_degraded = {h for h in multihost.hosts()
                    if circuit.host_degraded(h)}
    by_host: Dict[int, List[int]] = {}
    for did in device_ids:
        by_host.setdefault(multihost.host_of_id(did), []).append(did)
    dev_by_id = {d.id: d for d in (jax.devices() if HAVE_JAX else [])}
    retired: List[int] = []
    sick: List[int] = []
    for host, ids in sorted(by_host.items()):
        if not multihost.local_addressable(host):
            continue  # agreement, not local probes, owns remote hosts
        bad = []
        for did in ids:
            dev = dev_by_id.get(did)

            def probe(d=dev):
                x = jax.device_put(np.arange(8, dtype=np.uint8), d)
                return np.asarray(x + 1)

            ok = dev is not None and circuit.probe_raw(
                f"{circuit.DEVICE_FAMILY_PREFIX}{did}", probe,
                devices=(did,), timeout=_probe_timeout())
            with _lock:
                _counters["mesh_probes"] += 1
            if not ok:
                bad.append(did)
        if not bad:
            continue
        if len(bad) == len(ids):
            # the whole host's complement failed: ONE event
            circuit.retire_host(host)
            retired.append(host)
            with _lock:
                _counters["host_retirements"] += 1
        else:
            for did in bad:
                circuit.device_breaker(did).record_failure()
                sick.append(did)
    if multihost.is_multiprocess():
        multihost.membership_changed()
        healthy = [d.id for d in dev_by_id.values()
                   if not circuit.device_degraded(d.id)]
        multihost.agreed_healthy(healthy)  # retires unreachable hosts
        # report only hosts that became degraded IN THIS round —
        # earlier retirements still in backoff are not this
        # failure's attribution
        retired.extend(h for h in multihost.hosts()
                       if circuit.host_degraded(h)
                       and h not in pre_degraded
                       and h not in retired)
    return retired, sick


def _mesh_dispatch(family: str, key: tuple, plan: ExecPlan,
                   args: tuple, batch: int) -> Tuple[str, object]:
    """One mesh-plan dispatch with sick-chip / lost-host attribution.
    Returns ("ok", out) / ("oom", None) / ("shrunk", None) — a sick
    chip or dead host was found and retired, re-plan on the survivors
    — / ("fail", None) — a genuine (non-chip) failure, fall to the
    single-device plan."""
    status, out = _guarded(family, key, plan, args, batch,
                           defer_verdict=True)
    if status == "ok":
        with _lock:
            _counters["mesh_dispatches"] += 1
            _counters["mesh_rows"] += batch
        return "ok", out
    if status == "oom":
        return "oom", None
    if status == "open":
        return "fail", None
    if _host_aware():
        hosts_lost, sick = _attribute_failure(plan.devices)
    else:
        hosts_lost, sick = [], _probe_devices(plan.devices)
    if hosts_lost or sick:
        # the chip's/host's breaker owns the fault (tripped by its
        # probe / the membership verdict); the family must not stay
        # tripped or every caller would degrade to host — the point
        # of the shrink is that they re-plan instead.  Losing a host
        # is ONE shrink, exactly like losing one chip.
        circuit.breaker(family).absolve()
        with _lock:
            _counters["mesh_shrinks"] += 1
        tracing.event(
            f"mesh shrink: host(s) {hosts_lost} / device(s) {sick}"
            " retired" if hosts_lost else
            f"mesh shrink: sick device(s) {sick} retired")
        return "shrunk", None
    _note_plan_failure(key)
    return "fail", None


def mesh_dispatches() -> int:
    """Monotone mesh-dispatch count (the encode service reads the
    delta around a flush to report mesh_batches)."""
    with _lock:
        return _counters["mesh_dispatches"]


def mesh_info() -> dict:
    """Admin view of the mesh policy + live health: the device_health
    tell command and meshbench surface this."""
    total, healthy = 0, []
    if HAVE_JAX and gf.backend_available():
        try:
            devs = jax.devices()
            total = len(devs)
            healthy = [d.id for d in devs
                       if not circuit.device_degraded(d.id)]
        except Exception:
            pass
    with _lock:
        counters = {k: _counters[k] for k in
                    ("mesh_dispatches", "mesh_rows", "mesh_shrinks",
                     "mesh_probes", "host_retirements")}
    out = {
        "enabled": mesh_enabled(),
        "devices_total": total,
        "healthy": healthy,
        "min_bytes": _mesh_min_bytes(),
        "min_stripes": _mesh_min_stripes(),
        **counters,
    }
    # host failure-domain topology (the multihost seam): process
    # count, per-host device sets, per-host health
    try:
        from ceph_tpu.parallel import multihost

        out["hosts"] = {
            str(h): {"devices": list(ids),
                     "degraded": int(circuit.host_degraded(h))}
            for h, ids in sorted(multihost.hosts().items())}
        out["host_count"] = multihost.host_count()
        out["processes"] = multihost.process_count()
        out["multihost_enabled"] = multihost.enabled()
    except Exception:  # pragma: no cover
        pass
    return out


# ---------------------------------------------------------------------------
# Plan kinds
# ---------------------------------------------------------------------------


# pick caches: matrix signature -> XorSchedule | ("dense", naive),
# and schedule sig -> tracked jit.  Reached concurrently from the
# event loop AND the encode service's to_thread workers, so every
# access takes the lock (LruCache.peek's get-then-move_to_end is not
# atomic under eviction); compiles/jits happen OUTSIDE it — a racing
# pair builds twice, last write wins, both results identical
_sched_lock = threading.Lock()
_sched_pick = LruCache(cap=64)
_direct_jits = LruCache(cap=32)


def _sched_for(matrix: np.ndarray):
    """The compiled XOR schedule of a GF(2^8) matrix's bit expansion,
    memoized by matrix signature, or None when the kill switch is
    off / the matrix is too dense to ever clear the op-count pick.
    The density pre-bound matters: Paar CSE is quadratic-ish in the
    ones count, and a wide-k expansion whose BEST case still exceeds
    the unroll ceiling must not pay a multi-second compile on its
    first dispatch just to be rejected.  The cache stores the
    schedule (or the naive count for too-dense matrices) rather than
    the verdict, so the policy knobs — `xsched.prefer_schedule` AND
    the density bound below — are re-judged per call and stay live."""
    if not xsched.enabled():
        return None
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    msig = matrix_signature(m)
    with _sched_lock:
        sched = _sched_pick.peek(msig)
    if sched is None:
        bits = gf.gf_matrix_to_bits(m)
        naive = int(bits.sum()) - bits.shape[0]
        if naive // 4 > xsched._max_ops():
            # even a 75% CSE cut (past the best the literature
            # reports) could not fit the unroll ceiling: remember
            # the COUNT, not the verdict, and skip the compile
            sched = ("dense", naive)
        else:
            sched = xsched.compile_matrix(bits, sig=f"{msig}/bits")
        with _sched_lock:
            _sched_pick.put(msig, sched)
    if isinstance(sched, tuple):        # ("dense", naive): re-judge
        if sched[1] // 4 > xsched._max_ops():
            return None
        with _sched_lock:               # the ceiling was raised:
            _sched_pick.pop(msig)       # compile on the next call
        return _sched_for(m)
    return sched


def _sched_impl(sched):
    """The device lowering of one XOR schedule: the SAME GF(2) math
    as _gf2_matmul_bytes_impl (unpack bit planes, combine, pack) but
    combined by the compiled XOR program instead of one dense
    matmul — xors_scheduled region XORs instead of an (8R x 8K)
    contraction.  Profitable exactly when xsched.prefer_schedule
    says so (sparse bitmatrix-family expansions)."""
    n_in = sched.n_in

    def impl(data):
        bits = gf._unpack_bits(data)          # (..., 8K, S) 0/1
        tmp = [None] * sched.n_slots

        def ref(r):
            return bits[..., r, :] if r < n_in else tmp[r - n_in]

        for dst, a, b in sched.ops:
            tmp[dst] = jnp.bitwise_xor(ref(a), ref(b))
        rows = []
        for refs in sched.outputs:
            if not refs:
                rows.append(jnp.zeros_like(bits[..., 0, :]))
                continue
            acc = ref(refs[0])
            for r in refs[1:]:
                acc = jnp.bitwise_xor(acc, ref(r))
            rows.append(acc)
        return gf._pack_bits(jnp.stack(rows, axis=-2))

    return impl


def _build_xor_sched(key: tuple, sched) -> ExecPlan:
    """The `xor_sched` plan kind: the schedule lowering jitted per
    bucketed shape, riding the same guard/quarantine/OOM discipline
    as every other plan.  The schedule is baked into the trace (its
    signature IS the key prefix), so unlike the matmul kind there is
    no runtime matrix operand."""
    jfn = tracked_jit(_label(key), _sched_impl(sched))
    return ExecPlan(key, jfn, "xla_xor_sched")


def xor_sched_direct(matrix: np.ndarray):
    """Schedule-vs-matmul pick for direct (non-plan-cached)
    ops/gf.gf_matmul_device consumers: the jitted shape-polymorphic
    schedule executor when the measured op count prefers it, else
    None (caller keeps the dense bit-matmul).  Jits are memoized per
    schedule signature and tracked, so retraces stay visible in
    plan.stats()."""
    if not HAVE_JAX:
        return None
    sched = _sched_for(np.asarray(matrix, dtype=np.uint8))
    if sched is None or not xsched.prefer_schedule(sched):
        return None
    with _sched_lock:
        fn = _direct_jits.peek(sched.sig)
    if fn is None:
        fn = tracked_jit(f"xor_sched_direct[{sched.sig}]",
                         _sched_impl(sched))
        with _sched_lock:
            _direct_jits.put(sched.sig, fn)
    return fn


def _build_local_encode(key: tuple, donate: bool) -> ExecPlan:
    """Single-dispatch XLA bit-matmul plan; the bit matrix rides as a
    runtime operand so same-geometry matrices share the compile."""
    kw = {"donate_argnums": (1,)} if donate else {}
    jfn = tracked_jit(_label(key), gf._gf2_matmul_bytes_impl, **kw)

    def run(mbits, padded_dev):
        return jfn(mbits, padded_dev)

    return ExecPlan(key, run, "xla_bits" + ("+donate" if donate else ""))


def _wrap_gather(jfn: Callable) -> Callable:
    """Cross-process plans hold only their addressable output shards
    per process; materialize through the allgather so _guarded's
    np.asarray (and the watchdog) see the whole result.  Identity in
    every single-process shape."""
    from ceph_tpu.parallel import multihost

    if not multihost.is_multiprocess():
        return jfn

    def run(*args):
        return multihost.gather(jfn(*args))

    return run


def _build_mesh_encode(key: tuple, devices: tuple) -> ExecPlan:
    """Stripe-parallel mesh twin of the local encode plan: the same
    bit-matmul shard_mapped over a stripe-parallel mesh of the
    surviving chips — hybrid ("dcn", "dp") when they span hosts, flat
    ("dp",) within one (parallel/striped.py owns the kernel + the
    logical axis rules)."""
    from ceph_tpu.parallel import striped

    mesh = striped.stripe_mesh(list(devices))
    jfn, sharding = striped.build_mesh_encode(mesh, _label(key))
    return ExecPlan(key, _wrap_gather(jfn),
                    f"mesh_bits[{len(devices)}]",
                    sharding=sharding,
                    devices=tuple(d.id for d in devices))


def _build_mesh_encode_crc(key: tuple, devices: tuple,
                           chunk_bytes: int) -> ExecPlan:
    """Mesh twin of the fused encode+crc plan (the flush path's
    product shape): parity and the hinfo CRC stay device-resident
    between the stages of ONE stripe-parallel dispatch."""
    from ceph_tpu.parallel import striped

    mesh = striped.stripe_mesh(list(devices))
    jfn, sharding = striped.build_mesh_encode_crc(
        mesh, chunk_bytes, _label(key))
    return ExecPlan(key, _wrap_gather(jfn),
                    f"mesh_bits+crc[{len(devices)}]",
                    sharding=sharding,
                    devices=tuple(d.id for d in devices))


def _mesh_encode_attempt(kind: str, family: str, matrix: np.ndarray,
                         arr: np.ndarray, sig: str, rows: int,
                         k: int, b: int, s: int
                         ) -> Tuple[str, Optional[object]]:
    """Try an encode-kind dispatch on the healthy mesh, shrinking on
    sick chips.  Returns ("none", None) — take the single-device
    plan — or ("ok", out) / ("oom", None).  Out is the raw padded
    plan output; callers slice."""
    devices = _mesh_devices(b, b * k * s)
    for _attempt in range(8):       # shrink at most once per domain
        if not devices:
            return "none", None
        ids = tuple(d.id for d in devices)
        key = plan_key(sig, kind, rows, k, b, s, mesh=ids,
                       proc=_topology())
        if _quarantined(key):
            return "none", None
        if kind == "mesh_encode_crc":
            plan = _get_plan(
                key, lambda: _build_mesh_encode_crc(key, devices, s))
        else:
            plan = _get_plan(
                key, lambda: _build_mesh_encode(key, devices))
        bb, bs = key[4], key[5]
        # shard straight from host bytes in ONE device_put — landing
        # on the default device first and re-scattering would double
        # the transfer on the flush hot path.  Cross-process plans
        # assemble the global array from each process's addressable
        # shards instead (the SPMD contract: every process holds the
        # same logical batch).
        from ceph_tpu.parallel import multihost

        padded = multihost.put_global(_pad_batch(arr, bb, bs),
                                      plan.sharding)
        status, out = _mesh_dispatch(
            family, key, plan, (_mbits_for(matrix), padded), b)
        if status in ("ok", "oom"):
            return status, out
        if status != "shrunk":
            return "none", None
        devices = _mesh_devices(b, b * k * s)  # the survivors
    return "none", None


def encode(matrix: np.ndarray, data: np.ndarray, sig: str = None,
           donate: Optional[bool] = None,
           family: str = "ec-encode") -> Optional[np.ndarray]:
    """(B, K, S) or (K, S) uint8 stripes -> parity, plan-cached.

    Donation policy: None (auto) donates only the padded device buffer
    this function itself creates from host bytes; True asserts the
    caller relinquishes a device-resident input; False never donates.
    Off-TPU backends never donate (XLA would ignore it).  Returns None
    when no jax backend is available, the plan key is quarantined, or
    the dispatch failed past the guard (callers take the bit-exact
    host path); RESOURCE_EXHAUSTED recursively halves the batch down
    to a single stripe before giving up.
    """
    if not (HAVE_JAX and gf.backend_available()):
        return None
    arr = np.asarray(data, dtype=np.uint8) if isinstance(
        data, np.ndarray) else data
    host_input = isinstance(arr, np.ndarray)
    squeeze = False
    if (arr.ndim if host_input else len(arr.shape)) == 2:
        arr = arr[None]
        squeeze = True
    b, k, s = arr.shape
    if s == 0:
        return None
    rows = int(np.asarray(matrix).shape[0])
    sig = sig or matrix_signature(matrix)

    def halve() -> Optional[np.ndarray]:
        # OOM halving: each half re-buckets onto a smaller plan; GF
        # parity is per-stripe independent, so the split is bit-exact
        h = b // 2
        first = encode(matrix, arr[:h], sig=sig, donate=donate,
                       family=family)
        second = encode(matrix, arr[h:], sig=sig, donate=donate,
                        family=family)
        if first is None or second is None:
            return None
        out = np.concatenate([first, second], axis=0)
        return out[0] if squeeze else out

    if host_input:
        # mesh attempt first: big-enough host batches shard over the
        # healthy chips (device-resident inputs follow the caller's
        # donation contract and stay on their single device)
        mstatus, mout = _mesh_encode_attempt(
            "mesh_encode", family, matrix, arr, sig, rows, k, b, s)
        if mstatus == "ok":
            out = np.asarray(mout)[:b, :, :s]
            return out[0] if squeeze else out
        if mstatus == "oom" and b > 1:
            return halve()
    # schedule-vs-matmul pick (the xor_sched plan kind): a sparse
    # bitmatrix-family expansion whose compiled XOR program beats the
    # dense bit-matmul by measured op count dispatches the program
    # instead.  The picked kind OWNS the dispatch — a failed or
    # quarantined xor_sched plan degrades to the bit-exact HOST path
    # (one plan key per call, exactly like the matmul kind), never to
    # a second compiled plan
    sched = _sched_for(np.asarray(matrix, dtype=np.uint8)) \
        if host_input else None
    if sched is not None and xsched.prefer_schedule(sched):
        skey = plan_key(sched.sig, "xor_sched", rows, k, b, s)
        if _quarantined(skey):
            return None
        splan = _get_plan(
            skey, lambda: _build_xor_sched(skey, sched))
        padded = jnp.asarray(_pad_batch(arr, skey[4], skey[5]))
        status, out = _guarded(family, skey, splan, (padded,), b)
        if status == "oom" and b > 1:
            return halve()
        if status != "ok":
            return None
        out = np.asarray(out)[:b, :, :s]
        return out[0] if squeeze else out
    eff_donate = bool(_donation_usable()
                      and (donate or (donate is None and host_input)))
    key = plan_key(sig, "encode", rows, k, b, s, donate=eff_donate)
    if _quarantined(key):
        return None
    plan = _get_plan(
        key, lambda: _build_local_encode(key, eff_donate))
    bb, bs = key[4], key[5]
    if host_input:
        padded = jnp.asarray(_pad_batch(arr, bb, bs))
    else:
        # device-resident input: only donated when the caller opted in
        # (donate=True), so no defensive copy is ever needed
        pad = ((0, bb - b), (0, 0), (0, bs - s))
        padded = jnp.pad(arr, pad) if (bb != b or bs != s) else arr
    status, out = _guarded(family, key, plan,
                           (_mbits_for(matrix), padded), b)
    if status == "oom" and b > 1:
        return halve()
    if status != "ok":
        return None
    out = np.asarray(out)[:b, :, :s]
    return out[0] if squeeze else out


def _build_compute(key: tuple, weights: np.ndarray) -> ExecPlan:
    """The `compute` plan kind: a coded-compute kernel evaluation —
    a row-weighted XOR fold of the (B, rows, lanes) batch of shard
    streams, one trace shared by every wave that lands in the same
    bucket.  The weight row is a COMPILE-TIME constant (the key
    carries its content signature), so all-ones kernels lower to a
    pure XOR reduce instead of a GF table walk."""
    from ceph_tpu.compute import kernels as compute_kernels

    jfn = tracked_jit(_label(key),
                      compute_kernels.make_device_eval(weights))
    return ExecPlan(key, jfn, "xla_fold")


def compute_eval(name: str, weights: np.ndarray, data: np.ndarray,
                 sig: Optional[str] = None,
                 family: str = "compute") -> Optional[np.ndarray]:
    """(B, rows, lanes) uint8 shard batch -> (B, 1, lanes) kernel
    results through the plan cache (kind `compute`, its own breaker
    family so a compute fault never degrades the encode/decode data
    path).  Returns None when no jax backend is available, the plan
    is quarantined, or the guarded dispatch failed — callers take the
    bit-exact numpy host path; RESOURCE_EXHAUSTED halves the batch
    recursively first."""
    if not (HAVE_JAX and gf.backend_available()):
        return None
    arr = np.asarray(data, dtype=np.uint8)
    assert arr.ndim == 3, arr.shape
    b, rows, lanes = arr.shape
    if b == 0 or rows == 0 or lanes == 0:
        return None
    w = np.asarray(weights, dtype=np.uint8)
    sig = sig or matrix_signature(w, extra=f"compute/{name}")
    key = plan_key(sig, "compute", 1, rows, b, lanes)
    if _quarantined(key):
        return None
    plan = _get_plan(key, lambda: _build_compute(key, w))
    bb, bs = key[4], key[5]
    padded = jnp.asarray(_pad_batch(arr, bb, bs))
    status, out = _guarded(family, key, plan, (padded,), b)
    if status == "oom" and b > 1:
        h = b // 2
        first = compute_eval(name, w, arr[:h], sig=sig,
                             family=family)
        second = compute_eval(name, w, arr[h:], sig=sig,
                              family=family)
        if first is None or second is None:
            return None
        return np.concatenate([first, second], axis=0)
    if status != "ok":
        return None
    return np.asarray(out)[:b, :, :lanes]


def _build_inference(key: tuple, arch: str) -> ExecPlan:
    """The `inference` plan kind: batched query-x-shard scoring for
    the coded inference engine — every serving stream's forward pass
    over the query batch in ONE dispatch.  Unlike the compute kind
    the parameters are RUNTIME operands (each stored model differs;
    baking them would compile per model), so one trace per
    (arch, dims, query bucket) serves every model of that shape."""
    if arch == "linear":
        def fwd(tables, q):
            # (B, rows, dim) x (nq, dim) -> (B, nq, rows)
            return jnp.einsum("qd,brd->bqr", q, tables,
                              preferred_element_type=jnp.float32)
    else:
        def fwd(w1, b1, w2, q):
            # (B,h,dim),(B,h),(B,o,h) x (nq,dim) -> (B, nq, o)
            hid = jnp.maximum(
                jnp.einsum("qd,bhd->bqh", q, w1,
                           preferred_element_type=jnp.float32)
                + b1[:, None, :], 0.0)
            return jnp.einsum("bqh,boh->bqo", hid, w2,
                              preferred_element_type=jnp.float32)
    return ExecPlan(key, tracked_jit(_label(key), fwd), "xla_infer")


def inference_eval(arch: str, ops: tuple, queries: np.ndarray,
                   sig: str, family: str = "ec-inference"
                   ) -> Optional[np.ndarray]:
    """Stacked per-stream parameters + (nq, dim) query batch ->
    (B, nq, cols) float32 contributions through the plan cache (kind
    `inference`, its own breaker family so an inference fault never
    trips the encode/decode or compute paths).  The sig must encode
    ALL parameter dims (they are runtime operands, invisible to the
    key otherwise); only the query batch rides the bucketed axis.
    Returns None on no backend / quarantine / guarded failure —
    callers take the bit-exact numpy forward (model.shard_forward);
    RESOURCE_EXHAUSTED halves the query batch recursively first."""
    if not (HAVE_JAX and gf.backend_available()):
        return None
    q = np.asarray(queries, dtype=np.float32)
    nq = q.shape[0]
    nstreams = ops[0].shape[0]
    if nq == 0 or nstreams == 0:
        return None
    key = plan_key(sig, "inference", nstreams, 0, nq, 0)
    if _quarantined(key):
        return None
    plan = _get_plan(key, lambda: _build_inference(key, arch))
    bq = key[4]
    qp = np.pad(q, ((0, bq - nq), (0, 0))) if bq != nq else q
    status, out = _guarded(
        family, key, plan,
        tuple(jnp.asarray(np.asarray(o, dtype=np.float32))
              for o in ops) + (jnp.asarray(qp),), nq)
    if status == "oom" and nq > 1:
        h = nq // 2
        first = inference_eval(arch, ops, q[:h], sig, family=family)
        second = inference_eval(arch, ops, q[h:], sig, family=family)
        if first is None or second is None:
            return None
        return np.concatenate([first, second], axis=1)
    if status != "ok":
        return None
    return np.asarray(out)[:, :nq, :]


def _build_repair(key: tuple, matrix: np.ndarray) -> ExecPlan:
    """The `repair` plan kind: a regenerating-code repair matmul —
    helper-side projection rows or the primary's reconstruction
    matrix — where the matrix is a COMPILE-TIME constant like the
    compute kind's weight row (the key carries its content
    signature, so one plan serves every wave of the same codec +
    erasure pattern).  Repair matrices are tiny (alpha x d), so
    baking them lets XLA fold the bit expansion into the trace
    instead of shipping a runtime operand per dispatch."""
    mbits = jnp.asarray(gf.gf_matrix_to_bits(
        np.ascontiguousarray(matrix, dtype=np.uint8)))
    jfn = tracked_jit(_label(key),
                      lambda d: gf._gf2_matmul_bytes_impl(mbits, d))
    return ExecPlan(key, jfn, "xla_bits_const")


def repair(mat: np.ndarray, data, sig: Optional[str] = None,
           family: str = "ec-repair") -> Optional[np.ndarray]:
    """(B, D, S) or (D, S) uint8 helper fragments x the (R, D) repair
    matrix -> lost sub-chunk rows, plan-cached (kind `repair`).

    The plan key hashes the MATRIX CONTENT (the caller's sig rides as
    a cache-locality extra only) because the matrix is baked into the
    trace — correctness must not depend on callers keeping sigs
    matrix-unique.  Same schedule-vs-matmul pick as the encode kind:
    a sparse bit expansion whose compiled XOR program wins by op
    count dispatches as an xor_sched plan instead.  Returns None when
    no jax backend is available, the plan key is quarantined, or the
    guarded dispatch failed (callers take the bit-exact host path);
    RESOURCE_EXHAUSTED halves the batch recursively first."""
    if not (HAVE_JAX and gf.backend_available()):
        return None
    if not isinstance(data, np.ndarray):
        return None
    arr = np.asarray(data, dtype=np.uint8)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[None]
    if arr.ndim != 3:
        return None
    b, kk, s = arr.shape
    if s == 0 or b == 0:
        return None
    mat = np.ascontiguousarray(np.asarray(mat, dtype=np.uint8))
    rows = mat.shape[0]
    sig = matrix_signature(mat, extra=sig or "repair")

    def halve() -> Optional[np.ndarray]:
        h = b // 2
        first = repair(mat, arr[:h], sig=sig, family=family)
        second = repair(mat, arr[h:], sig=sig, family=family)
        if first is None or second is None:
            return None
        out = np.concatenate([first, second], axis=0)
        return out[0] if squeeze else out

    sched = _sched_for(mat)
    if sched is not None and xsched.prefer_schedule(sched):
        skey = plan_key(sched.sig, "xor_sched", rows, kk, b, s)
        if _quarantined(skey):
            return None
        splan = _get_plan(skey, lambda: _build_xor_sched(skey, sched))
        padded = jnp.asarray(_pad_batch(arr, skey[4], skey[5]))
        status, out = _guarded(family, skey, splan, (padded,), b)
        if status == "oom" and b > 1:
            return halve()
        if status != "ok":
            return None
        out = np.asarray(out)[:b, :, :s]
        return out[0] if squeeze else out
    key = plan_key(sig, "repair", rows, kk, b, s)
    if _quarantined(key):
        return None
    plan = _get_plan(key, lambda: _build_repair(key, mat))
    padded = jnp.asarray(_pad_batch(arr, key[4], key[5]))
    status, out = _guarded(family, key, plan, (padded,), b)
    if status == "oom" and b > 1:
        return halve()
    if status != "ok":
        return None
    out = np.asarray(out)[:b, :, :s]
    return out[0] if squeeze else out


def _build_mesh_matmul(key: tuple) -> ExecPlan:
    """Delegate to the healthy-set sharded pipeline (its per-shape
    jits are tracked_jit'd in parallel/striped.py, so retraces land in
    the same counters).  The key's mesh element is the device-id set
    the pipeline rides — it doubles as the device_call attribution
    set, so the sick-device injection seam and per-chip success
    accounting see decode dispatches too."""
    from ceph_tpu.parallel import backend

    return ExecPlan(key, backend.matmul, "mesh", devices=key[7])


def matmul(mat: np.ndarray, data, sig: str = None,
           family: str = "ec-decode") -> Optional[np.ndarray]:
    """Plan-cached device GF(2^8) matmul — the ec/dispatch device
    entry.  Buckets the (B, S) shape, pads, dispatches through the
    cached plan, slices the real shape back out.  Returns None when no
    device path applies, the plan key is quarantined, or the guarded
    dispatch failed (caller falls back to host); RESOURCE_EXHAUSTED
    halves the batch recursively first."""
    if not (HAVE_JAX and gf.backend_available()):
        return None
    if not isinstance(data, np.ndarray):
        return None
    arr = np.asarray(data, dtype=np.uint8)
    squeeze = False
    if arr.ndim == 2:
        arr = arr[None]
        squeeze = True
    b, k, s = arr.shape
    if s == 0 or s % 4:
        return None
    mat = np.asarray(mat, dtype=np.uint8)
    rows = mat.shape[0]
    from ceph_tpu.parallel import backend

    status, out = None, None
    for _attempt in range(8):           # shrink at most once per chip
        # decode matrices cycle per erasure signature: key on shape
        # (matrix as runtime operand) + the LIVE healthy device set —
        # a shrink retires the dead chip's plans by key miss
        mesh_sig = backend.mesh_device_ids()
        key = plan_key(sig or "*", "matmul", rows, k, b, s,
                       mesh=mesh_sig, proc=_topology())
        if _quarantined(key):
            return None
        plan = _get_plan(key, lambda: _build_mesh_matmul(key))
        bb, bs = key[4], key[5]
        args = (mat, _pad_batch(arr, bb, bs))
        if len(mesh_sig) > 1:
            status, out = _mesh_dispatch(family, key, plan, args, b)
            if status == "shrunk":
                continue                # re-plan on the survivors
            if status == "fail":
                with _lock:
                    _counters["host_fallbacks"] += 1
                return None
        else:
            status, out = _guarded(family, key, plan, args, b)
        break
    if status == "oom" and b > 1:
        h = b // 2
        first = matmul(mat, arr[:h], sig=sig, family=family)
        second = matmul(mat, arr[h:], sig=sig, family=family)
        if first is None or second is None:
            return None
        out = np.concatenate([first, second], axis=0)
        return out[0] if squeeze else out
    if status != "ok" or out is None:
        return None
    out = np.asarray(out)[:b, :, :s]
    return out[0] if squeeze else out


def fused_encode_crc_step(mbits, d, consts):
    """THE fused parity + per-chunk zero-seeded crc32c kernel — the
    one trace both the single-device plan and the mesh builders
    (parallel/striped.build_mesh_encode_crc) wrap.  Bit-exact
    single-vs-mesh parity depends on them tracing identical math, so
    there is exactly one definition."""
    parity = gf._gf2_matmul_bytes_impl(mbits, d)
    chunks = jnp.concatenate([d, parity], axis=1)
    bits = cks.crc32c_partial_bits(chunks, consts)
    return parity, cks.crc32c_pack_bits(bits)


def _build_encode_crc(key: tuple) -> ExecPlan:
    """Fused parity + per-chunk zero-seeded crc32c in ONE dispatch
    (parity and the ECUtil::HashInfo ledger used to be two round
    trips).  The chunk-byte axis is NOT bucketed here — a CRC is
    length-exact — so the key carries the exact S; only the stripe
    batch pads (padded stripes' crcs are sliced off with the parity).
    """
    s = key[5]
    consts = cks.make_crc_consts(s)

    def impl(mbits, d):
        return fused_encode_crc_step(mbits, d, consts)

    jfn = tracked_jit(_label(key), impl)
    return ExecPlan(key, jfn, "xla_bits+crc")


def encode_with_crc(matrix: np.ndarray, data: np.ndarray,
                    sig: str = None
                    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(B, K, S) stripes -> (parity (B, M, S), crc (B, K+M) uint32).

    crcs are ZERO-seeded per-chunk crc32c (seed advances are host
    scalars: crc32c(init, chunk) = crc32c_zeros(init, S) ^ crc0);
    callers fold them into cumulative HashInfo ledgers.  Returns None
    when no jax backend is available.
    """
    if not (HAVE_JAX and gf.backend_available()):
        return None
    arr = np.asarray(data, dtype=np.uint8)
    assert arr.ndim == 3, arr.shape
    b, k, s = arr.shape
    if s == 0:
        return None
    rows = int(np.asarray(matrix).shape[0])
    sig = sig or matrix_signature(matrix)
    # mesh attempt first: the encode service's flush batches land
    # here — one stripe-parallel dispatch over the healthy chips,
    # parity + CRC fused on-device
    mstatus, mout = _mesh_encode_attempt(
        "mesh_encode_crc", "fused-crc", matrix, arr, sig, rows, k,
        b, s)
    if mstatus == "ok":
        mparity, mcrcs = mout
        return (np.asarray(mparity)[:b],
                np.asarray(mcrcs).astype(np.uint32)[:b])
    if mstatus == "oom" and b > 1:
        h = b // 2
        first = encode_with_crc(matrix, arr[:h], sig=sig)
        second = encode_with_crc(matrix, arr[h:], sig=sig)
        if first is None or second is None:
            return None
        return (np.concatenate([first[0], second[0]], axis=0),
                np.concatenate([first[1], second[1]], axis=0))
    key = plan_key(sig, "encode_crc", rows, k, b, s)
    if _quarantined(key):
        return None
    plan = _get_plan(key, lambda: _build_encode_crc(key))
    bb = key[4]
    padded = jnp.asarray(_pad_batch(arr, bb, s))
    status, out = _guarded("fused-crc", key, plan,
                           (_mbits_for(matrix), padded), b)
    if status == "oom" and b > 1:
        h = b // 2
        first = encode_with_crc(matrix, arr[:h], sig=sig)
        second = encode_with_crc(matrix, arr[h:], sig=sig)
        if first is None or second is None:
            return None
        return (np.concatenate([first[0], second[0]], axis=0),
                np.concatenate([first[1], second[1]], axis=0))
    if status != "ok":
        return None
    parity, crcs = out
    return (np.asarray(parity)[:b],
            np.asarray(crcs).astype(np.uint32)[:b])


# ---------------------------------------------------------------------------
# Stripe coalescing
# ---------------------------------------------------------------------------


def encode_coalesced(matrix: np.ndarray,
                     datas: Sequence[np.ndarray], sig: str = None
                     ) -> List[np.ndarray]:
    """Fold N pending same-profile (K, S_i) encodes into batched
    (B, K, S) device calls — the device twin of the host-path fold in
    ec/dispatch.gf_matmul.  Stripes are grouped by byte bucket (one
    2 MiB outlier must not inflate 63 pending 4 KiB stripes to its
    width), padded to the group bucket, and each parity sliced back to
    its own width; same-bucket traffic — the common case — stays ONE
    dispatch.  A jax-free host fallback keeps the contract."""
    if not datas:
        return []
    arrs = [np.asarray(d, dtype=np.uint8) for d in datas]
    k = arrs[0].shape[0]
    for a in arrs:
        assert a.ndim == 2 and a.shape[0] == k, a.shape
    groups: Dict[int, List[int]] = {}
    for i, a in enumerate(arrs):
        groups.setdefault(bucket_bytes(a.shape[1]), []).append(i)
    out: List[Optional[np.ndarray]] = [None] * len(arrs)
    for bs, idxs in groups.items():
        batch = np.zeros((len(idxs), k, bs), dtype=np.uint8)
        for row, i in enumerate(idxs):
            batch[row, :, :arrs[i].shape[1]] = arrs[i]
        parity = encode(matrix, batch, sig=sig)
        if parity is None:
            from ceph_tpu.ec import dispatch

            parity = dispatch.gf_matmul(np.asarray(matrix, np.uint8),
                                        batch, use_tpu=False)
        for row, i in enumerate(idxs):
            out[i] = parity[row, :, :arrs[i].shape[1]]
    return out


class StripeCoalescer:
    """Accumulates pending same-profile encode requests and serves
    them all from one batched device dispatch on flush().

    The OSD-side usage shape: enqueue each small stripe as it arrives
    (`add` returns its ticket), flush when the batch window closes,
    then pick results up by ticket.
    """

    def __init__(self, matrix: np.ndarray, sig: str = None,
                 max_pending: int = 64):
        self.matrix = np.asarray(matrix, dtype=np.uint8)
        self.sig = sig or matrix_signature(self.matrix)
        self.max_pending = max_pending
        self._pending: List[np.ndarray] = []

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def full(self) -> bool:
        return len(self._pending) >= self.max_pending

    def add(self, data: np.ndarray) -> int:
        """Queue one (K, S) stripe; returns its ticket (flush-order
        index)."""
        arr = np.asarray(data, dtype=np.uint8)
        assert arr.ndim == 2 and arr.shape[0] == self.matrix.shape[1], \
            arr.shape
        self._pending.append(arr)
        return len(self._pending) - 1

    def flush(self) -> List[np.ndarray]:
        """Encode everything pending in one batched dispatch; returns
        parities in ticket order."""
        pending, self._pending = self._pending, []
        if not pending:
            return []
        return encode_coalesced(self.matrix, pending, sig=self.sig)

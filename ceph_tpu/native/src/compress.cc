// Host-side block compression codecs for ceph_tpu.
//
// Capability parity with the reference's compressor plugins
// (/root/reference/src/compressor/{lz4,snappy}/): the reference links
// liblz4/libsnappy; this build has neither, so both block formats are
// implemented here from their public format specifications.  The framing
// above (compression_header, required-ratio gate) lives in Python
// (ceph_tpu/compressor); these are the raw block codecs.
//
//   - LZ4 block format: token (4b literal len | 4b match len-4), 255-run
//     length extensions, 2-byte LE match offset, last-5-bytes-literal and
//     12-byte end-of-match rules per the spec.
//   - Snappy format: varint uncompressed length, then tagged elements
//     (literal / copy with 1, 2 or 4 byte offsets).
//
// Both compressors are greedy hash-table matchers tuned for throughput,
// not ratio records; both decompressors bounds-check untrusted input and
// return -1 on corruption.

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// shared helpers
// ---------------------------------------------------------------------------

static inline uint32_t read32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint16_t read16(const uint8_t *p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;
}

static inline void write16(uint8_t *p, uint16_t v) { memcpy(p, &v, 2); }

// Fibonacci-style multiplicative hash of a 4-byte window.
static inline uint32_t hash4(uint32_t v, int bits) {
  return (v * 2654435761u) >> (32 - bits);
}

// Length of the common prefix of a and b, at most limit.
static inline uint64_t match_length(const uint8_t *a, const uint8_t *b,
                                    uint64_t limit) {
  uint64_t n = 0;
  while (n + 8 <= limit) {
    uint64_t x, y;
    memcpy(&x, a + n, 8);
    memcpy(&y, b + n, 8);
    if (x != y) {
      return n + (__builtin_ctzll(x ^ y) >> 3);
    }
    n += 8;
  }
  while (n < limit && a[n] == b[n]) n++;
  return n;
}

// ---------------------------------------------------------------------------
// LZ4 block format
// ---------------------------------------------------------------------------

static const int LZ4_HASH_BITS = 16;
static const uint64_t LZ4_MFLIMIT = 12;      // last match must start before n-12
static const uint64_t LZ4_LASTLITERALS = 5;  // final 5 bytes are always literals
static const uint32_t LZ4_MAX_OFFSET = 65535;

uint64_t ceph_tpu_lz4_compress_bound(uint64_t n) {
  return n + n / 255 + 16;
}

// Returns compressed size, or -1 if dst_cap is too small.
int64_t ceph_tpu_lz4_compress(const uint8_t *src, uint64_t n, uint8_t *dst,
                              uint64_t dst_cap) {
  uint8_t *op = dst;
  uint8_t *oend = dst + dst_cap;
  uint32_t table[1u << LZ4_HASH_BITS];
  memset(table, 0xff, sizeof(table));

  uint64_t anchor = 0, pos = 0;
  const uint64_t mflimit = n > LZ4_MFLIMIT ? n - LZ4_MFLIMIT : 0;
  const uint64_t matchlimit = n > LZ4_LASTLITERALS ? n - LZ4_LASTLITERALS : 0;

  auto emit = [&](uint64_t lit_start, uint64_t lit_len, uint32_t offset,
                  uint64_t mlen) -> bool {
    // worst-case bytes for this sequence
    uint64_t need = 1 + lit_len / 255 + 1 + lit_len + 2 + mlen / 255 + 1;
    if (op + need > oend) return false;
    uint8_t *token = op++;
    uint64_t ll = lit_len;
    if (ll >= 15) {
      *token = 15 << 4;
      ll -= 15;
      while (ll >= 255) { *op++ = 255; ll -= 255; }
      *op++ = (uint8_t)ll;
    } else {
      *token = (uint8_t)(ll << 4);
    }
    memcpy(op, src + lit_start, lit_len);
    op += lit_len;
    if (mlen == 0) return true;  // final literal-only sequence
    write16(op, (uint16_t)offset);
    op += 2;
    uint64_t ml = mlen - 4;
    if (ml >= 15) {
      *token |= 15;
      ml -= 15;
      while (ml >= 255) { *op++ = 255; ml -= 255; }
      *op++ = (uint8_t)ml;
    } else {
      *token |= (uint8_t)ml;
    }
    return true;
  };

  if (n >= LZ4_MFLIMIT + 1) {
    while (pos < mflimit) {
      uint32_t seq = read32(src + pos);
      uint32_t h = hash4(seq, LZ4_HASH_BITS);
      uint32_t ref = table[h];
      table[h] = (uint32_t)pos;
      if (ref != 0xffffffffu && pos - ref <= LZ4_MAX_OFFSET &&
          read32(src + ref) == seq) {
        uint64_t mlen =
            4 + match_length(src + ref + 4, src + pos + 4, matchlimit - (pos + 4));
        if (!emit(anchor, pos - anchor, (uint32_t)(pos - ref), mlen)) return -1;
        pos += mlen;
        anchor = pos;
      } else {
        pos++;
      }
    }
  }
  if (!emit(anchor, n - anchor, 0, 0)) return -1;
  return op - dst;
}

// Returns decompressed size, or -1 on malformed input / undersized dst.
int64_t ceph_tpu_lz4_decompress(const uint8_t *src, uint64_t n, uint8_t *dst,
                                uint64_t dst_cap) {
  const uint8_t *ip = src, *iend = src + n;
  uint8_t *op = dst, *oend = dst + dst_cap;

  while (ip < iend) {
    uint8_t token = *ip++;
    uint64_t ll = token >> 4;
    if (ll == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        ll += b;
      } while (b == 255);
    }
    if (ip + ll > iend || op + ll > oend) return -1;
    memcpy(op, ip, ll);
    ip += ll;
    op += ll;
    if (ip == iend) break;  // last sequence has no match
    if (ip + 2 > iend) return -1;
    uint32_t offset = read16(ip);
    ip += 2;
    if (offset == 0 || (uint64_t)(op - dst) < offset) return -1;
    uint64_t ml = (token & 15);
    if (ml == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        ml += b;
      } while (b == 255);
    }
    ml += 4;
    if (op + ml > oend) return -1;
    const uint8_t *match = op - offset;
    if (offset >= 8) {
      // non-overlapping enough for 8-byte strides
      uint64_t i = 0;
      for (; i + 8 <= ml; i += 8) memcpy(op + i, match + i, 8);
      for (; i < ml; i++) op[i] = match[i];
    } else {
      for (uint64_t i = 0; i < ml; i++) op[i] = match[i];
    }
    op += ml;
  }
  return op - dst;
}

// ---------------------------------------------------------------------------
// Snappy format
// ---------------------------------------------------------------------------

static const int SNAPPY_HASH_BITS = 14;

uint64_t ceph_tpu_snappy_compress_bound(uint64_t n) {
  return 32 + n + n / 6;
}

static inline uint8_t *snappy_emit_literal(uint8_t *op, const uint8_t *lit,
                                           uint64_t len) {
  uint64_t l = len - 1;
  if (l < 60) {
    *op++ = (uint8_t)(l << 2);
  } else {
    int count = 0;
    uint64_t tmp = l;
    while (tmp > 0) { count++; tmp >>= 8; }
    *op++ = (uint8_t)((59 + count) << 2);
    for (int i = 0; i < count; i++) *op++ = (uint8_t)(l >> (8 * i));
  }
  memcpy(op, lit, len);
  return op + len;
}

// One copy element, length 4..64, offset < 65536.
static inline uint8_t *snappy_emit_copy_chunk(uint8_t *op, uint32_t offset,
                                              uint64_t len) {
  if (len < 12 && offset < 2048) {
    *op++ = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
    *op++ = (uint8_t)offset;
  } else {
    *op++ = (uint8_t)(2 | ((len - 1) << 2));
    write16(op, (uint16_t)offset);
    op += 2;
  }
  return op;
}

static inline uint8_t *snappy_emit_copy(uint8_t *op, uint32_t offset,
                                        uint64_t len) {
  while (len >= 68) {
    op = snappy_emit_copy_chunk(op, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    op = snappy_emit_copy_chunk(op, offset, 60);
    len -= 60;
  }
  return snappy_emit_copy_chunk(op, offset, len);
}

int64_t ceph_tpu_snappy_compress(const uint8_t *src, uint64_t n, uint8_t *dst,
                                 uint64_t dst_cap) {
  if (n >= (1ull << 32)) return -1;  // snappy length fields are 32-bit
  if (dst_cap < ceph_tpu_snappy_compress_bound(n)) return -1;
  uint8_t *op = dst;
  // varint uncompressed length
  uint64_t v = n;
  while (v >= 0x80) {
    *op++ = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  *op++ = (uint8_t)v;

  uint32_t table[1u << SNAPPY_HASH_BITS];
  memset(table, 0xff, sizeof(table));

  uint64_t anchor = 0, pos = 0;
  const uint64_t limit = n > 15 ? n - 15 : 0;  // keep 4-byte reads in bounds
  while (pos < limit) {
    uint32_t seq = read32(src + pos);
    uint32_t h = hash4(seq, SNAPPY_HASH_BITS);
    uint32_t ref = table[h];
    table[h] = (uint32_t)pos;
    if (ref != 0xffffffffu && pos - ref <= 65535 && read32(src + ref) == seq) {
      uint64_t mlen = 4 + match_length(src + ref + 4, src + pos + 4, n - pos - 4);
      if (pos > anchor) op = snappy_emit_literal(op, src + anchor, pos - anchor);
      op = snappy_emit_copy(op, (uint32_t)(pos - ref), mlen);
      pos += mlen;
      anchor = pos;
    } else {
      pos++;
    }
  }
  if (n > anchor) op = snappy_emit_literal(op, src + anchor, n - anchor);
  return op - dst;
}

// Parses the varint length header; returns it, or -1 if malformed.
int64_t ceph_tpu_snappy_uncompressed_length(const uint8_t *src, uint64_t n) {
  uint64_t v = 0;
  int shift = 0;
  for (uint64_t i = 0; i < n && shift < 35; i++) {
    v |= (uint64_t)(src[i] & 0x7f) << shift;
    if (!(src[i] & 0x80)) return (int64_t)v;
    shift += 7;
  }
  return -1;
}

int64_t ceph_tpu_snappy_decompress(const uint8_t *src, uint64_t n, uint8_t *dst,
                                   uint64_t dst_cap) {
  // skip varint header
  uint64_t hdr = 0;
  while (hdr < n && (src[hdr] & 0x80)) hdr++;
  if (hdr >= n) return -1;
  hdr++;
  int64_t want = ceph_tpu_snappy_uncompressed_length(src, n);
  if (want < 0 || (uint64_t)want > dst_cap) return -1;

  const uint8_t *ip = src + hdr, *iend = src + n;
  uint8_t *op = dst, *oend = dst + dst_cap;
  while (ip < iend) {
    uint8_t tag = *ip++;
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      uint64_t len = (tag >> 2) + 1;
      if (len > 60) {
        int count = (int)len - 60;
        if (ip + count > iend) return -1;
        len = 0;
        for (int i = 0; i < count; i++) len |= (uint64_t)ip[i] << (8 * i);
        len += 1;
        ip += count;
      }
      if (ip + len > iend || op + len > oend) return -1;
      memcpy(op, ip, len);
      ip += len;
      op += len;
    } else {
      uint64_t len;
      uint32_t offset;
      if (kind == 1) {
        if (ip >= iend) return -1;
        len = ((tag >> 2) & 7) + 4;
        offset = ((uint32_t)(tag >> 5) << 8) | *ip++;
      } else if (kind == 2) {
        if (ip + 2 > iend) return -1;
        len = (tag >> 2) + 1;
        offset = read16(ip);
        ip += 2;
      } else {
        if (ip + 4 > iend) return -1;
        len = (tag >> 2) + 1;
        offset = read32(ip);
        ip += 4;
      }
      if (offset == 0 || (uint64_t)(op - dst) < offset || op + len > oend)
        return -1;
      const uint8_t *match = op - offset;
      for (uint64_t i = 0; i < len; i++) op[i] = match[i];
      op += len;
    }
  }
  return (op - dst) == want ? want : -1;
}

}  // extern "C"

// Host-side checksum & GF(2^8) region kernels for ceph_tpu.
//
// Capability parity with the reference's native checksum layer:
//   - crc32c (Castagnoli): /root/reference/src/include/crc32c.h:43-50 —
//     ceph_crc32c(seed, data, len) with NO pre/post inversion; data==NULL
//     means "len zero bytes".
//   - ceph_crc32c_zeros: /root/reference/src/common/crc32c.cc:216-239 —
//     O(log len) advance of a crc through a run of zeros.  The reference
//     uses a precomputed 32x32 "turbo" table per power-of-two range; here
//     the same math is GF(2) 32x32 matrix squaring computed at startup.
//   - xxhash32/64: vendored xxHash in the reference (src/xxHash/); here a
//     from-spec implementation (XXH32/XXH64, seedable).
//   - GF(2^8) region multiply-accumulate: the scalar-fallback analog of
//     isa-l/jerasure region ops (src/erasure-code/isa/xor_op.cc) used by the
//     host (non-TPU) erasure-code path.
//
// The TPU path for bulk data lives in JAX/Pallas (ceph_tpu/ops); this file
// is the low-latency host runtime for small buffers, metadata, and tests.
//
// Build: g++ -O3 -shared -fPIC (driven by ceph_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// crc32c (Castagnoli, reflected poly 0x82F63B78), slicing-by-8
// ---------------------------------------------------------------------------

static uint32_t crc_table[8][256];

static void crc32c_init_tables() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc_table[0][i];
    for (int t = 1; t < 8; t++) {
      c = crc_table[0][c & 0xff] ^ (c >> 8);
      crc_table[t][i] = c;
    }
  }
}

// GF(2) 32x32 matrices for zero-run folding (column b = image of bit b).
static void gf2_matmul_vec(const uint32_t m[32], uint32_t *crc) {
  uint32_t out = 0, v = *crc;
  for (int b = 0; v; b++, v >>= 1)
    if (v & 1) out ^= m[b];
  *crc = out;
}

static void gf2_matmul_mat(const uint32_t a[32], const uint32_t b[32],
                           uint32_t out[32]) {
  for (int i = 0; i < 32; i++) {
    uint32_t v = b[i];
    gf2_matmul_vec(a, &v);
    out[i] = v;
  }
}

// zero_mat[r] advances a crc through 2^r zero bytes.
static uint32_t zero_mat[64][32];

static void crc32c_init_zero_mats() {
  for (int b = 0; b < 32; b++) {  // one zero byte
    uint32_t s = 1u << b;
    zero_mat[0][b] = crc_table[0][s & 0xff] ^ (s >> 8);
  }
  for (int r = 1; r < 64; r++)
    gf2_matmul_mat(zero_mat[r - 1], zero_mat[r - 1], zero_mat[r]);
}

uint32_t ceph_tpu_crc32c_zeros(uint32_t crc, uint64_t len) {
  for (int r = 0; len; r++, len >>= 1)
    if (len & 1) gf2_matmul_vec(zero_mat[r], &crc);
  return crc;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t *data, uint64_t len) {
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = crc_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t w;
    std::memcpy(&w, data, 8);
    w ^= crc;
    crc = crc_table[7][w & 0xff] ^ crc_table[6][(w >> 8) & 0xff] ^
          crc_table[5][(w >> 16) & 0xff] ^ crc_table[4][(w >> 24) & 0xff] ^
          crc_table[3][(w >> 32) & 0xff] ^ crc_table[2][(w >> 40) & 0xff] ^
          crc_table[1][(w >> 48) & 0xff] ^ crc_table[0][(w >> 56) & 0xff];
    data += 8;
    len -= 8;
  }
  while (len--) crc = crc_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
// Hardware CRC32C (the SSE4.2 crc32 instruction computes exactly the
// Castagnoli reflected CRC) — the crc32c_intel_fast role
// (/root/reference/src/common/crc32c_intel_fast.c); ~10x the
// slicing-by-8 tables.
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw_1way(uint32_t crc, const uint8_t *data,
                               uint64_t len) {
  uint64_t c = crc;
  while (len >= 8) {
    uint64_t w;
    std::memcpy(&w, data, 8);
    c = __builtin_ia32_crc32di(c, w);
    data += 8;
    len -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (len--) c32 = __builtin_ia32_crc32qi(c32, *data++);
  return c32;
}

// The crc32 instruction has ~3-cycle latency, 1-cycle throughput: a
// single dependency chain caps at ~2.7 B/cycle.  Three independent
// lanes fill the pipeline (~8 B/cycle), recombined through zero-run
// advance folds — the standard interleave the reference's asm tier
// implements with PCLMULQDQ folding.
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t *data, uint64_t len) {
  constexpr uint64_t MIN3 = 3 * 256;
  if (len < MIN3) return crc32c_hw_1way(crc, data, len);
  uint64_t lane = (len / 24) * 8;  // 8-byte-aligned lane length
  const uint8_t *pa = data, *pb = data + lane, *pc = data + 2 * lane;
  uint64_t a = crc, b = 0, c = 0;
  for (uint64_t i = 0; i < lane; i += 8) {
    uint64_t wa, wb, wc;
    std::memcpy(&wa, pa + i, 8);
    std::memcpy(&wb, pb + i, 8);
    std::memcpy(&wc, pc + i, 8);
    a = __builtin_ia32_crc32di(a, wa);
    b = __builtin_ia32_crc32di(b, wb);
    c = __builtin_ia32_crc32di(c, wc);
  }
  uint64_t tail = len - 3 * lane;
  uint32_t a32 = static_cast<uint32_t>(a);
  uint32_t b32 = static_cast<uint32_t>(b);
  uint32_t c32 = static_cast<uint32_t>(c);
  // result = advance(a, 2*lane + tail) ^ advance(b, lane + tail) ^
  //          crc(c seeded 0 over partC+tail).  Advances are O(log n)
  //          zero-run vector folds — race-free, cache-free, and cheap
  //          against >=768-byte lanes.
  c32 = crc32c_hw_1way(c32, data + 3 * lane, tail);
  a32 = ceph_tpu_crc32c_zeros(a32, 2 * lane + tail);
  b32 = ceph_tpu_crc32c_zeros(b32, lane + tail);
  return a32 ^ b32 ^ c32;
}

static bool have_sse42() {
  static const bool ok = __builtin_cpu_supports("sse4.2");
  return ok;
}
#endif

uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t *data, uint64_t len) {
  if (data == nullptr) return ceph_tpu_crc32c_zeros(crc, len);
#if defined(__x86_64__)
  if (have_sse42()) return crc32c_hw(crc, data, len);
#endif
  return crc32c_sw(crc, data, len);
}

// Per-block crc32c over a contiguous buffer of nblocks x block_size bytes
// (the Checksummer inner loop; Checksummer.h calc() per csum_block_size).
void ceph_tpu_crc32c_blocks(const uint8_t *data, uint64_t nblocks,
                            uint64_t block_size, uint32_t init,
                            uint32_t *out) {
  for (uint64_t i = 0; i < nblocks; i++)
    out[i] = ceph_tpu_crc32c(init, data + i * block_size, block_size);
}

// crc32c combine: crc(AB) from crc(A), crc(B), len(B)  (bufferlist-style
// cached-crc composition, src/common/buffer.cc crc path).
uint32_t ceph_tpu_crc32c_combine(uint32_t crc_a, uint32_t crc_b,
                                 uint64_t len_b) {
  return ceph_tpu_crc32c_zeros(crc_a, len_b) ^ crc_b;
}

// ---------------------------------------------------------------------------
// xxHash32 / xxHash64 (from the public spec; seedable)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}
static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint32_t read32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
static inline uint64_t read64(const uint8_t *p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

static const uint32_t P32_1 = 2654435761u, P32_2 = 2246822519u,
                      P32_3 = 3266489917u, P32_4 = 668265263u,
                      P32_5 = 374761393u;

uint32_t ceph_tpu_xxh32(const uint8_t *data, uint64_t len, uint32_t seed) {
  const uint8_t *p = data, *end = data + len;
  uint32_t h;
  if (len >= 16) {
    uint32_t v1 = seed + P32_1 + P32_2, v2 = seed + P32_2, v3 = seed,
             v4 = seed - P32_1;
    const uint8_t *limit = end - 16;
    do {
      v1 = rotl32(v1 + read32(p) * P32_2, 13) * P32_1; p += 4;
      v2 = rotl32(v2 + read32(p) * P32_2, 13) * P32_1; p += 4;
      v3 = rotl32(v3 + read32(p) * P32_2, 13) * P32_1; p += 4;
      v4 = rotl32(v4 + read32(p) * P32_2, 13) * P32_1; p += 4;
    } while (p <= limit);
    h = rotl32(v1, 1) + rotl32(v2, 7) + rotl32(v3, 12) + rotl32(v4, 18);
  } else {
    h = seed + P32_5;
  }
  h += (uint32_t)len;
  while (p + 4 <= end) {
    h = rotl32(h + read32(p) * P32_3, 17) * P32_4;
    p += 4;
  }
  while (p < end) {
    h = rotl32(h + (*p) * P32_5, 11) * P32_1;
    p++;
  }
  h ^= h >> 15; h *= P32_2; h ^= h >> 13; h *= P32_3; h ^= h >> 16;
  return h;
}

static const uint64_t P64_1 = 11400714785074694791ull,
                      P64_2 = 14029467366897019727ull,
                      P64_3 = 1609587929392839161ull,
                      P64_4 = 9650029242287828579ull,
                      P64_5 = 2870177450012600261ull;

static inline uint64_t xxh64_round(uint64_t acc, uint64_t input) {
  return rotl64(acc + input * P64_2, 31) * P64_1;
}
static inline uint64_t xxh64_merge(uint64_t h, uint64_t v) {
  h ^= xxh64_round(0, v);
  return h * P64_1 + P64_4;
}

uint64_t ceph_tpu_xxh64(const uint8_t *data, uint64_t len, uint64_t seed) {
  const uint8_t *p = data, *end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P64_1 + P64_2, v2 = seed + P64_2, v3 = seed,
             v4 = seed - P64_1;
    const uint8_t *limit = end - 32;
    do {
      v1 = xxh64_round(v1, read64(p)); p += 8;
      v2 = xxh64_round(v2, read64(p)); p += 8;
      v3 = xxh64_round(v3, read64(p)); p += 8;
      v4 = xxh64_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh64_merge(h, v1);
    h = xxh64_merge(h, v2);
    h = xxh64_merge(h, v3);
    h = xxh64_merge(h, v4);
  } else {
    h = seed + P64_5;
  }
  h += len;
  while (p + 8 <= end) {
    h ^= xxh64_round(0, read64(p));
    h = rotl64(h, 27) * P64_1 + P64_4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P64_1;
    h = rotl64(h, 23) * P64_2 + P64_3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P64_5;
    h = rotl64(h, 11) * P64_1;
    p++;
  }
  h ^= h >> 33; h *= P64_2; h ^= h >> 29; h *= P64_3; h ^= h >> 32;
  return h;
}

void ceph_tpu_xxh32_blocks(const uint8_t *data, uint64_t nblocks,
                           uint64_t block_size, uint32_t seed, uint32_t *out) {
  for (uint64_t i = 0; i < nblocks; i++)
    out[i] = ceph_tpu_xxh32(data + i * block_size, block_size, seed);
}

void ceph_tpu_xxh64_blocks(const uint8_t *data, uint64_t nblocks,
                           uint64_t block_size, uint64_t seed, uint64_t *out) {
  for (uint64_t i = 0; i < nblocks; i++)
    out[i] = ceph_tpu_xxh64(data + i * block_size, block_size, seed);
}

// ---------------------------------------------------------------------------
// GF(2^8) region ops (host fallback for the erasure-code data path)
// ---------------------------------------------------------------------------

// dst ^= src over len bytes, word-at-a-time (xor_op.cc vector XOR analog).
void ceph_tpu_region_xor(uint8_t *dst, const uint8_t *src, uint64_t len) {
  uint64_t i = 0;
  for (; i + 8 <= len; i += 8) {
    uint64_t a, b;
    std::memcpy(&a, dst + i, 8);
    std::memcpy(&b, src + i, 8);
    a ^= b;
    std::memcpy(dst + i, &a, 8);
  }
  for (; i < len; i++) dst[i] ^= src[i];
}

// dst ^= mul_table[src] over len bytes; mul_table is the 256-entry GF(2^8)
// multiply table of one matrix coefficient (jerasure region multiply analog).
void ceph_tpu_region_mad(uint8_t *dst, const uint8_t *src, uint64_t len,
                         const uint8_t *mul_table) {
  for (uint64_t i = 0; i < len; i++) dst[i] ^= mul_table[src[i]];
}

// GF(2^8) matmul on host: out(R,S) = mat(R,K) * data(K,S) with XOR
// accumulation, using per-coefficient 256-entry tables supplied by Python
// (tables laid out as mat.size x 256).
void ceph_tpu_gf_matmul(const uint8_t *mat_tables, uint64_t r, uint64_t k,
                        const uint8_t *data, uint64_t s, uint8_t *out) {
  std::memset(out, 0, r * s);
  for (uint64_t j = 0; j < r; j++)
    for (uint64_t i = 0; i < k; i++) {
      const uint8_t *tbl = mat_tables + (j * k + i) * 256;
      if (tbl[1] == 0) continue;  // coefficient 0: table all zero
      ceph_tpu_region_mad(out + j * s, data + i * s, s, tbl);
    }
}

struct NativeInit {
  NativeInit() {
    crc32c_init_tables();
    crc32c_init_zero_mats();
  }
};
static NativeInit _init;

}  // extern "C"

// AES-256-GCM for msgr2 secure mode.
//
// Reference parity: the reference encrypts secure-mode frames with
// AES-GCM through OpenSSL (/root/reference/src/msg/async/crypto_onwire.cc
// AES128GCM_OnWireTxHandler).  This is an independent implementation of
// the published algorithms (FIPS-197 AES, NIST SP 800-38D GCM): a
// portable software path that runs anywhere, plus an AES-NI/PCLMULQDQ
// fast path compiled with per-function target attributes and selected
// at runtime (the build stays plain -O3, no -march flags).
//
// Contract (bound via ctypes in ceph_tpu/native/__init__.py):
//   seal: out = ciphertext(ptlen) || tag(16), returns 0
//   open: ctlen INCLUDES the 16-byte tag; out = plaintext; returns 0,
//         or -1 on tag mismatch (out is zeroed — never release
//         unauthenticated plaintext)

#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(__i386__)
#define CEPH_TPU_X86 1
#include <immintrin.h>
#include <wmmintrin.h>
#endif

namespace {

// ---------------------------------------------------------------- AES core

static const uint8_t SBOX[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67,
    0x2b, 0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59,
    0x47, 0xf0, 0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7,
    0xfd, 0x93, 0x26, 0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1,
    0x71, 0xd8, 0x31, 0x15, 0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05,
    0x9a, 0x07, 0x12, 0x80, 0xe2, 0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83,
    0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0, 0x52, 0x3b, 0xd6, 0xb3, 0x29,
    0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed, 0x20, 0xfc, 0xb1, 0x5b,
    0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf, 0xd0, 0xef, 0xaa,
    0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f, 0x50, 0x3c,
    0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5, 0xbc,
    0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19,
    0x73, 0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee,
    0xb8, 0x14, 0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49,
    0x06, 0x24, 0x5c, 0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79,
    0xe7, 0xc8, 0x37, 0x6d, 0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4,
    0xea, 0x65, 0x7a, 0xae, 0x08, 0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6,
    0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f, 0x4b, 0xbd, 0x8b, 0x8a, 0x70,
    0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e, 0x61, 0x35, 0x57, 0xb9,
    0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11, 0x69, 0xd9, 0x8e,
    0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf, 0x8c, 0xa1,
    0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f, 0xb0,
    0x54, 0xbb, 0x16};

static const uint8_t RCON[15] = {0x00, 0x01, 0x02, 0x04, 0x08, 0x10,
                                 0x20, 0x40, 0x80, 0x1b, 0x36, 0x6c,
                                 0xd8, 0xab, 0x4d};

struct AesKey {
    // AES-256: 14 rounds, 15 round keys of 16 bytes
    uint8_t rk[15][16];
};

static void key_expand(const uint8_t key[32], AesKey* ks) {
    uint8_t w[60][4];  // Nb*(Nr+1) = 60 words
    memcpy(w, key, 32);
    for (int i = 8; i < 60; i++) {
        uint8_t t[4];
        memcpy(t, w[i - 1], 4);
        if (i % 8 == 0) {
            uint8_t tmp = t[0];  // RotWord
            t[0] = SBOX[t[1]] ^ RCON[i / 8];
            t[1] = SBOX[t[2]];
            t[2] = SBOX[t[3]];
            t[3] = SBOX[tmp];
        } else if (i % 8 == 4) {
            for (int j = 0; j < 4; j++) t[j] = SBOX[t[j]];
        }
        for (int j = 0; j < 4; j++) w[i][j] = w[i - 8][j] ^ t[j];
    }
    memcpy(ks->rk, w, 240);
}

static inline uint8_t xtime(uint8_t x) {
    return (uint8_t)((x << 1) ^ ((x >> 7) * 0x1b));
}

static void encrypt_block_soft(const AesKey* ks, const uint8_t in[16],
                               uint8_t out[16]) {
    uint8_t s[16];
    for (int i = 0; i < 16; i++) s[i] = in[i] ^ ks->rk[0][i];
    for (int round = 1; round <= 14; round++) {
        uint8_t t[16];
        // SubBytes + ShiftRows fused: t[c*4+r] = SBOX[s[((c+r)%4)*4+r]]
        for (int c = 0; c < 4; c++)
            for (int r = 0; r < 4; r++)
                t[c * 4 + r] = SBOX[s[((c + r) & 3) * 4 + r]];
        if (round < 14) {
            for (int c = 0; c < 4; c++) {  // MixColumns
                uint8_t* p = t + c * 4;
                uint8_t a0 = p[0], a1 = p[1], a2 = p[2], a3 = p[3];
                uint8_t x = a0 ^ a1 ^ a2 ^ a3;
                p[0] = a0 ^ x ^ xtime(a0 ^ a1);
                p[1] = a1 ^ x ^ xtime(a1 ^ a2);
                p[2] = a2 ^ x ^ xtime(a2 ^ a3);
                p[3] = a3 ^ x ^ xtime(a3 ^ a0);
            }
        }
        for (int i = 0; i < 16; i++) s[i] = t[i] ^ ks->rk[round][i];
    }
    memcpy(out, s, 16);
}

// ---------------------------------------------------------------- GHASH

// GF(2^128) multiply, right-shift formulation (SP 800-38D 6.3).
// Portable fallback; the PCLMUL path below replaces it on x86-64.
static void gf_mult_soft(const uint8_t X[16], const uint8_t Y[16],
                         uint8_t out[16]) {
    uint8_t Z[16] = {0};
    uint8_t V[16];
    memcpy(V, Y, 16);
    for (int i = 0; i < 128; i++) {
        if (X[i >> 3] & (0x80u >> (i & 7)))
            for (int j = 0; j < 16; j++) Z[j] ^= V[j];
        int lsb = V[15] & 1;
        for (int j = 15; j > 0; j--)
            V[j] = (uint8_t)((V[j] >> 1) | (V[j - 1] << 7));
        V[0] >>= 1;
        if (lsb) V[0] ^= 0xE1;
    }
    memcpy(out, Z, 16);
}

struct Ghash {
    uint8_t H[16];
    uint8_t Y[16];
    bool use_clmul;
};

#ifdef CEPH_TPU_X86
__attribute__((target("aes")))
static void key_expand_ni_store(const uint8_t key[32], AesKey* ks) {
    // AES-256 key schedule via AESKEYGENASSIST (FIPS-197 expansion on
    // 128-bit lanes; the standard two-lane assist pattern)
    __m128i k0 = _mm_loadu_si128((const __m128i*)key);
    __m128i k1 = _mm_loadu_si128((const __m128i*)(key + 16));
    __m128i* out = (__m128i*)ks->rk;
    _mm_storeu_si128(out + 0, k0);
    _mm_storeu_si128(out + 1, k1);
    auto assist1 = [](__m128i a, __m128i b) {  // i%8==0 step
        b = _mm_shuffle_epi32(b, 0xff);
        a = _mm_xor_si128(a, _mm_slli_si128(a, 4));
        a = _mm_xor_si128(a, _mm_slli_si128(a, 4));
        a = _mm_xor_si128(a, _mm_slli_si128(a, 4));
        return _mm_xor_si128(a, b);
    };
#define EXPAND_ROUND(idx, rc)                                           \
    {                                                                   \
        __m128i t = _mm_aeskeygenassist_si128(k1, rc);                  \
        k0 = assist1(k0, t);                                            \
        _mm_storeu_si128(out + idx, k0);                                \
        if (idx < 14) {                                                 \
            __m128i t2 = _mm_aeskeygenassist_si128(k0, 0);              \
            t2 = _mm_shuffle_epi32(t2, 0xaa);                           \
            k1 = _mm_xor_si128(k1, _mm_slli_si128(k1, 4));              \
            k1 = _mm_xor_si128(k1, _mm_slli_si128(k1, 4));              \
            k1 = _mm_xor_si128(k1, _mm_slli_si128(k1, 4));              \
            k1 = _mm_xor_si128(k1, t2);                                 \
            _mm_storeu_si128(out + idx + 1, k1);                        \
        }                                                               \
    }
    EXPAND_ROUND(2, 0x01)
    EXPAND_ROUND(4, 0x02)
    EXPAND_ROUND(6, 0x04)
    EXPAND_ROUND(8, 0x08)
    EXPAND_ROUND(10, 0x10)
    EXPAND_ROUND(12, 0x20)
    EXPAND_ROUND(14, 0x40)
#undef EXPAND_ROUND
}

__attribute__((target("aes")))
static void encrypt_block_ni(const AesKey* ks, const uint8_t in[16],
                             uint8_t out[16]) {
    const __m128i* rk = (const __m128i*)ks->rk;
    __m128i s = _mm_loadu_si128((const __m128i*)in);
    s = _mm_xor_si128(s, _mm_loadu_si128(rk));
    for (int r = 1; r < 14; r++)
        s = _mm_aesenc_si128(s, _mm_loadu_si128(rk + r));
    s = _mm_aesenclast_si128(s, _mm_loadu_si128(rk + 14));
    _mm_storeu_si128((__m128i*)out, s);
}

// CTR over 4 blocks per iteration: AESENC pipelines across
// independent lanes, which is where AES-NI's throughput lives
__attribute__((target("aes")))
static void ctr_xor_ni(const AesKey* ks, uint8_t ctr[16],
                       const uint8_t* in, uint8_t* out, uint64_t len) {
    const __m128i* rk = (const __m128i*)ks->rk;
    uint32_t c = ((uint32_t)ctr[12] << 24) | ((uint32_t)ctr[13] << 16) |
                 ((uint32_t)ctr[14] << 8) | ctr[15];
    uint64_t off = 0;
    while (off < len) {
        __m128i blk[4];
        int lanes = (len - off > 48) ? 4 : (int)((len - off + 15) / 16);
        for (int l = 0; l < lanes; l++) {
            uint8_t cb[16];
            memcpy(cb, ctr, 12);
            uint32_t cc = ++c;
            cb[12] = (uint8_t)(cc >> 24);
            cb[13] = (uint8_t)(cc >> 16);
            cb[14] = (uint8_t)(cc >> 8);
            cb[15] = (uint8_t)cc;
            blk[l] = _mm_xor_si128(_mm_loadu_si128((__m128i*)cb),
                                   _mm_loadu_si128(rk));
        }
        for (int r = 1; r < 14; r++) {
            __m128i k = _mm_loadu_si128(rk + r);
            for (int l = 0; l < lanes; l++)
                blk[l] = _mm_aesenc_si128(blk[l], k);
        }
        __m128i klast = _mm_loadu_si128(rk + 14);
        for (int l = 0; l < lanes; l++)
            blk[l] = _mm_aesenclast_si128(blk[l], klast);
        for (int l = 0; l < lanes && off < len; l++) {
            uint8_t kb[16];
            _mm_storeu_si128((__m128i*)kb, blk[l]);
            uint64_t n = len - off < 16 ? len - off : 16;
            for (uint64_t i = 0; i < n; i++)
                out[off + i] = (uint8_t)(in[off + i] ^ kb[i]);
            off += n;
        }
    }
    ctr[12] = (uint8_t)(c >> 24);
    ctr[13] = (uint8_t)(c >> 16);
    ctr[14] = (uint8_t)(c >> 8);
    ctr[15] = (uint8_t)c;
}

// GHASH multiply via carry-less multiply + the standard bit-reflected
// reduction (SP 800-38D poly, Gueron/Kounavis formulation)
__attribute__((target("pclmul,ssse3")))
static void gf_mult_clmul(const uint8_t X[16], const uint8_t Y[16],
                          uint8_t out[16]) {
    const __m128i BSWAP =
        _mm_set_epi8(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14,
                     15);
    __m128i a = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)X),
                                 BSWAP);
    __m128i b = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)Y),
                                 BSWAP);
    __m128i t0 = _mm_clmulepi64_si128(a, b, 0x00);
    __m128i t1 = _mm_clmulepi64_si128(a, b, 0x10);
    __m128i t2 = _mm_clmulepi64_si128(a, b, 0x01);
    __m128i t3 = _mm_clmulepi64_si128(a, b, 0x11);
    t1 = _mm_xor_si128(t1, t2);
    t2 = _mm_slli_si128(t1, 8);
    t1 = _mm_srli_si128(t1, 8);
    t0 = _mm_xor_si128(t0, t2);   // low 128
    t3 = _mm_xor_si128(t3, t1);   // high 128
    // bit-reflection handling: shift the 256-bit product left by one
    __m128i lo = t0, hi = t3;
    __m128i lo_l = _mm_slli_epi64(lo, 1);
    __m128i lo_r = _mm_srli_epi64(lo, 63);
    __m128i hi_l = _mm_slli_epi64(hi, 1);
    __m128i hi_r = _mm_srli_epi64(hi, 63);
    __m128i carry_lo = _mm_slli_si128(lo_r, 8);
    __m128i carry_hi = _mm_or_si128(_mm_slli_si128(hi_r, 8),
                                    _mm_srli_si128(lo_r, 8));
    lo = _mm_or_si128(lo_l, carry_lo);
    hi = _mm_or_si128(hi_l, carry_hi);
    // reduce modulo x^128 + x^7 + x^2 + x + 1
    __m128i t7 = _mm_slli_epi64(lo, 57);
    __m128i t8 = _mm_slli_epi64(lo, 62);
    __m128i t9 = _mm_slli_epi64(lo, 63);
    __m128i tmp = _mm_xor_si128(t7, _mm_xor_si128(t8, t9));
    __m128i tl = _mm_slli_si128(tmp, 8);
    __m128i th = _mm_srli_si128(tmp, 8);
    lo = _mm_xor_si128(lo, tl);
    __m128i r1 = _mm_srli_epi64(lo, 1);
    __m128i r2 = _mm_srli_epi64(lo, 2);
    __m128i r7 = _mm_srli_epi64(lo, 7);
    __m128i red = _mm_xor_si128(r1, _mm_xor_si128(r2, r7));
    red = _mm_xor_si128(red, th);
    hi = _mm_xor_si128(hi, _mm_xor_si128(lo, red));
    _mm_storeu_si128((__m128i*)out, _mm_shuffle_epi8(hi, BSWAP));
}

static bool cpu_has_aes() {
    return __builtin_cpu_supports("aes") &&
           __builtin_cpu_supports("pclmul") &&
           __builtin_cpu_supports("ssse3");
}
#else
static bool cpu_has_aes() { return false; }
#endif

static void ghash_update(Ghash* g, const uint8_t* data, uint64_t len) {
    uint8_t blk[16];
    for (uint64_t off = 0; off < len; off += 16) {
        uint64_t n = len - off < 16 ? len - off : 16;
        memset(blk, 0, 16);
        memcpy(blk, data + off, n);
        for (int i = 0; i < 16; i++) g->Y[i] ^= blk[i];
#ifdef CEPH_TPU_X86
        if (g->use_clmul) {
            gf_mult_clmul(g->Y, g->H, g->Y);
            continue;
        }
#endif
        gf_mult_soft(g->Y, g->H, g->Y);
    }
}

static void ctr_xor_soft(const AesKey* ks, uint8_t ctr[16],
                         const uint8_t* in, uint8_t* out,
                         uint64_t len) {
    uint8_t kb[16];
    for (uint64_t off = 0; off < len; off += 16) {
        // increment the 32-bit big-endian counter (inc32)
        for (int i = 15; i >= 12; i--)
            if (++ctr[i]) break;
        encrypt_block_soft(ks, ctr, kb);
        uint64_t n = len - off < 16 ? len - off : 16;
        for (uint64_t i = 0; i < n; i++)
            out[off + i] = (uint8_t)(in[off + i] ^ kb[i]);
    }
}

static void gcm_crypt(const uint8_t* key, const uint8_t iv[12],
                      const uint8_t* aad, uint64_t aadlen,
                      const uint8_t* in, uint64_t len, uint8_t* out,
                      uint8_t tag[16], bool ghash_over_out) {
    AesKey ks;
    bool ni = cpu_has_aes();
#ifdef CEPH_TPU_X86
    if (ni)
        key_expand_ni_store(key, &ks);
    else
#endif
        key_expand(key, &ks);

    Ghash g;
    g.use_clmul = ni;
    memset(g.Y, 0, 16);
    uint8_t zero[16] = {0};
#ifdef CEPH_TPU_X86
    if (ni)
        encrypt_block_ni(&ks, zero, g.H);
    else
#endif
        encrypt_block_soft(&ks, zero, g.H);

    uint8_t j0[16];
    memcpy(j0, iv, 12);
    j0[12] = j0[13] = j0[14] = 0;
    j0[15] = 1;

    uint8_t ctr[16];
    memcpy(ctr, j0, 16);
#ifdef CEPH_TPU_X86
    if (ni)
        ctr_xor_ni(&ks, ctr, in, out, len);
    else
#endif
        ctr_xor_soft(&ks, ctr, in, out, len);

    ghash_update(&g, aad, aadlen);
    ghash_update(&g, ghash_over_out ? out : in, len);
    uint8_t lens[16];
    uint64_t ab = aadlen * 8, cb = len * 8;
    for (int i = 0; i < 8; i++) {
        lens[i] = (uint8_t)(ab >> (56 - 8 * i));
        lens[8 + i] = (uint8_t)(cb >> (56 - 8 * i));
    }
    ghash_update(&g, lens, 16);

    uint8_t ek0[16];
#ifdef CEPH_TPU_X86
    if (ni)
        encrypt_block_ni(&ks, j0, ek0);
    else
#endif
        encrypt_block_soft(&ks, j0, ek0);
    for (int i = 0; i < 16; i++) tag[i] = (uint8_t)(g.Y[i] ^ ek0[i]);
}

}  // namespace

extern "C" {

int ceph_tpu_aesgcm_seal(const uint8_t* key, const uint8_t* iv12,
                         const uint8_t* aad, uint64_t aadlen,
                         const uint8_t* pt, uint64_t ptlen,
                         uint8_t* out) {
    gcm_crypt(key, iv12, aad, aadlen, pt, ptlen, out, out + ptlen,
              /*ghash_over_out=*/true);
    return 0;
}

int ceph_tpu_aesgcm_open(const uint8_t* key, const uint8_t* iv12,
                         const uint8_t* aad, uint64_t aadlen,
                         const uint8_t* ct, uint64_t ctlen,
                         uint8_t* out) {
    if (ctlen < 16) return -1;
    uint64_t len = ctlen - 16;
    uint8_t tag[16];
    gcm_crypt(key, iv12, aad, aadlen, ct, len, out, tag,
              /*ghash_over_out=*/false);
    uint8_t diff = 0;  // constant-time tag compare
    for (int i = 0; i < 16; i++) diff |= (uint8_t)(tag[i] ^ ct[len + i]);
    if (diff) {
        memset(out, 0, len);
        return -1;
    }
    return 0;
}

}  // extern "C"

// GF(2^8) SIMD region kernels — the host-CPU speed tier.
//
// Role parity: the reference's vectorized GF region ops — Intel ISA-L's
// ec_encode_data / gf_vect_mad (used via
// /root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:119-131) and
// jerasure's SSE region multiply (src/erasure-code/jerasure/).  These give
// ceph_tpu an honest CPU baseline for bench.py's vs_baseline ratio and a
// fast host fallback for the ec_jax codec when no device is available.
//
// Technique: 4-bit split tables + (V)PSHUFB byte shuffle.  GF(2^8)
// multiplication by a constant c is GF(2)-linear in the input bits, so
//   c*x == c*(x & 0x0f) ^ c*(x & 0xf0)
// and each half is a 16-entry lookup — exactly the shape of the x86 byte
// shuffle instruction.  This is the well-known public method implemented
// by gf-complete ("SPLIT 8 4") and ISA-L; the code below is written from
// the technique, not copied from any implementation.

#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define CEPH_TPU_X86 1
#endif

namespace {

// Split a 256-entry multiply table into its two 16-entry nibble tables.
// Valid because the table is linear: tbl[x] == tbl[x & 0xf] ^ tbl[x & 0xf0].
inline void nibble_tables(const uint8_t *tbl, uint8_t lo[16],
                          uint8_t hi[16]) {
  for (int i = 0; i < 16; i++) {
    lo[i] = tbl[i];
    hi[i] = tbl[i << 4];
  }
}

void mad_scalar(uint8_t *dst, const uint8_t *src, uint64_t len,
                const uint8_t lo[16], const uint8_t hi[16]) {
  for (uint64_t i = 0; i < len; i++)
    dst[i] ^= lo[src[i] & 0x0f] ^ hi[src[i] >> 4];
}

#ifdef CEPH_TPU_X86

__attribute__((target("ssse3")))
void mad_ssse3(uint8_t *dst, const uint8_t *src, uint64_t len,
               const uint8_t lo[16], const uint8_t hi[16]) {
  const __m128i vlo = _mm_loadu_si128((const __m128i *)lo);
  const __m128i vhi = _mm_loadu_si128((const __m128i *)hi);
  const __m128i mask = _mm_set1_epi8(0x0f);
  uint64_t i = 0;
  for (; i + 16 <= len; i += 16) {
    __m128i s = _mm_loadu_si128((const __m128i *)(src + i));
    __m128i d = _mm_loadu_si128((const __m128i *)(dst + i));
    __m128i p = _mm_xor_si128(
        _mm_shuffle_epi8(vlo, _mm_and_si128(s, mask)),
        _mm_shuffle_epi8(vhi, _mm_and_si128(_mm_srli_epi64(s, 4), mask)));
    _mm_storeu_si128((__m128i *)(dst + i), _mm_xor_si128(d, p));
  }
  mad_scalar(dst + i, src + i, len - i, lo, hi);
}

__attribute__((target("avx2")))
void mad_avx2(uint8_t *dst, const uint8_t *src, uint64_t len,
              const uint8_t lo[16], const uint8_t hi[16]) {
  const __m256i vlo =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)lo));
  const __m256i vhi =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)hi));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  uint64_t i = 0;
  for (; i + 64 <= len; i += 64) {
    __m256i s0 = _mm256_loadu_si256((const __m256i *)(src + i));
    __m256i s1 = _mm256_loadu_si256((const __m256i *)(src + i + 32));
    __m256i d0 = _mm256_loadu_si256((const __m256i *)(dst + i));
    __m256i d1 = _mm256_loadu_si256((const __m256i *)(dst + i + 32));
    __m256i p0 = _mm256_xor_si256(
        _mm256_shuffle_epi8(vlo, _mm256_and_si256(s0, mask)),
        _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(s0, 4), mask)));
    __m256i p1 = _mm256_xor_si256(
        _mm256_shuffle_epi8(vlo, _mm256_and_si256(s1, mask)),
        _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(s1, 4), mask)));
    _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d0, p0));
    _mm256_storeu_si256((__m256i *)(dst + i + 32),
                        _mm256_xor_si256(d1, p1));
  }
  for (; i + 32 <= len; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i *)(src + i));
    __m256i d = _mm256_loadu_si256((const __m256i *)(dst + i));
    __m256i p = _mm256_xor_si256(
        _mm256_shuffle_epi8(vlo, _mm256_and_si256(s, mask)),
        _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(s, 4), mask)));
    _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d, p));
  }
  mad_scalar(dst + i, src + i, len - i, lo, hi);
}

__attribute__((target("ssse3")))
void mul_ssse3(uint8_t *dst, const uint8_t *src, uint64_t len,
               const uint8_t lo[16], const uint8_t hi[16]) {
  const __m128i vlo = _mm_loadu_si128((const __m128i *)lo);
  const __m128i vhi = _mm_loadu_si128((const __m128i *)hi);
  const __m128i mask = _mm_set1_epi8(0x0f);
  uint64_t i = 0;
  for (; i + 16 <= len; i += 16) {
    __m128i s = _mm_loadu_si128((const __m128i *)(src + i));
    __m128i p = _mm_xor_si128(
        _mm_shuffle_epi8(vlo, _mm_and_si128(s, mask)),
        _mm_shuffle_epi8(vhi, _mm_and_si128(_mm_srli_epi64(s, 4), mask)));
    _mm_storeu_si128((__m128i *)(dst + i), p);
  }
  for (; i < len; i++) dst[i] = lo[src[i] & 0x0f] ^ hi[src[i] >> 4];
}

__attribute__((target("avx2")))
void mul_avx2(uint8_t *dst, const uint8_t *src, uint64_t len,
              const uint8_t lo[16], const uint8_t hi[16]) {
  const __m256i vlo =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)lo));
  const __m256i vhi =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)hi));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  uint64_t i = 0;
  for (; i + 32 <= len; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i *)(src + i));
    __m256i p = _mm256_xor_si256(
        _mm256_shuffle_epi8(vlo, _mm256_and_si256(s, mask)),
        _mm256_shuffle_epi8(
            vhi, _mm256_and_si256(_mm256_srli_epi64(s, 4), mask)));
    _mm256_storeu_si256((__m256i *)(dst + i), p);
  }
  for (; i < len; i++) dst[i] = lo[src[i] & 0x0f] ^ hi[src[i] >> 4];
}

void mul_scalar(uint8_t *dst, const uint8_t *src, uint64_t len,
                const uint8_t lo[16], const uint8_t hi[16]) {
  for (uint64_t i = 0; i < len; i++)
    dst[i] = lo[src[i] & 0x0f] ^ hi[src[i] >> 4];
}

__attribute__((target("avx2")))
void xor_avx2(uint8_t *dst, const uint8_t *src, uint64_t len) {
  uint64_t i = 0;
  for (; i + 64 <= len; i += 64) {
    __m256i d0 = _mm256_loadu_si256((const __m256i *)(dst + i));
    __m256i d1 = _mm256_loadu_si256((const __m256i *)(dst + i + 32));
    __m256i s0 = _mm256_loadu_si256((const __m256i *)(src + i));
    __m256i s1 = _mm256_loadu_si256((const __m256i *)(src + i + 32));
    _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d0, s0));
    _mm256_storeu_si256((__m256i *)(dst + i + 32),
                        _mm256_xor_si256(d1, s1));
  }
  for (; i < len; i++) dst[i] ^= src[i];
}

#endif  // CEPH_TPU_X86

using mad_fn = void (*)(uint8_t *, const uint8_t *, uint64_t,
                        const uint8_t[16], const uint8_t[16]);

int detect_level() {
#ifdef CEPH_TPU_X86
  if (__builtin_cpu_supports("avx2")) return 2;
  if (__builtin_cpu_supports("ssse3")) return 1;
#endif
  return 0;
}

const int g_level = detect_level();

mad_fn pick_mad() {
#ifdef CEPH_TPU_X86
  if (g_level == 2) return mad_avx2;
  if (g_level == 1) return mad_ssse3;
#endif
  return mad_scalar;
}

const mad_fn g_mad = pick_mad();

mad_fn pick_mul() {
#ifdef CEPH_TPU_X86
  if (g_level == 2) return mul_avx2;
  if (g_level == 1) return mul_ssse3;
#endif
  return mul_scalar;
}

const mad_fn g_mul = pick_mul();

}  // namespace

extern "C" {

// declared in checksum.cc
void ceph_tpu_region_xor(uint8_t *dst, const uint8_t *src, uint64_t len);
void ceph_tpu_gf_matmul(const uint8_t *mat_tables, uint64_t r, uint64_t k,
                        const uint8_t *data, uint64_t s, uint8_t *out);

// 0 = scalar, 1 = SSSE3 (128-bit), 2 = AVX2 (256-bit)
int ceph_tpu_gf_simd_level(void) { return g_level; }

// dst ^= tbl[src] over len bytes, vectorized; tbl is a 256-entry GF(2^8)
// multiply table (one matrix coefficient).
void ceph_tpu_gf_region_mad_v(uint8_t *dst, const uint8_t *src,
                              uint64_t len, const uint8_t *tbl) {
  uint8_t lo[16], hi[16];
  nibble_tables(tbl, lo, hi);
  g_mad(dst, src, len, lo, hi);
}

// dst = tbl[src] (no accumulate): the first-column store that lets the
// encode loop skip a whole memset pass over the parity buffers.
void ceph_tpu_gf_region_mul_v(uint8_t *dst, const uint8_t *src,
                              uint64_t len, const uint8_t *tbl) {
  uint8_t lo[16], hi[16];
  nibble_tables(tbl, lo, hi);
  g_mul(dst, src, len, lo, hi);
}

// Vectorized GF(2^8) matmul: out(R,S) = mat(R,K) * data(K,S), XOR
// accumulation, strip-mined so the data strip stays in L1 across the R
// output rows.  Same signature family as ceph_tpu_gf_matmul (scalar).
void ceph_tpu_gf_matmul_simd(const uint8_t *mat_tables, uint64_t r,
                             uint64_t k, const uint8_t *data, uint64_t s,
                             uint8_t *out) {
  // pre-split tables live on the stack: bound the matrix size (far above
  // any real EC profile) and fall back to the scalar path beyond it
  constexpr uint64_t MAXRK = 64 * 64;
  if (r * k > MAXRK) {
    ceph_tpu_gf_matmul(mat_tables, r, k, data, s, out);
    return;
  }
  std::memset(out, 0, r * s);
  uint8_t lo[MAXRK][16], hi[MAXRK][16];
  uint8_t kind[MAXRK];  // 0 = zero coeff, 1 = identity (XOR), 2 = general
  for (uint64_t j = 0; j < r; j++)
    for (uint64_t i = 0; i < k; i++) {
      const uint8_t *tbl = mat_tables + (j * k + i) * 256;
      uint64_t idx = j * k + i;
      nibble_tables(tbl, lo[idx], hi[idx]);
      if (tbl[1] == 0)
        kind[idx] = 0;
      else if (tbl[1] == 1 && tbl[2] == 2 && tbl[255] == 255)
        kind[idx] = 1;
      else
        kind[idx] = 2;
    }
  constexpr uint64_t STRIP = 16 * 1024;
  for (uint64_t off = 0; off < s; off += STRIP) {
    uint64_t n = (s - off < STRIP) ? (s - off) : STRIP;
    for (uint64_t j = 0; j < r; j++) {
      uint8_t *dst = out + j * s + off;
      for (uint64_t i = 0; i < k; i++) {
        const uint8_t *src = data + i * s + off;
        uint64_t idx = j * k + i;
        if (kind[idx] == 0) continue;
        if (kind[idx] == 1) {
#ifdef CEPH_TPU_X86
          if (g_level == 2) {
            xor_avx2(dst, src, n);
            continue;
          }
#endif
          ceph_tpu_region_xor(dst, src, n);
        } else {
          g_mad(dst, src, n, lo[idx], hi[idx]);
        }
      }
    }
  }
}

}  // extern "C"

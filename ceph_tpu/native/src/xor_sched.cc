// Native executor for compiled XOR schedules (ec/xsched.py).
//
// The codec compiler (PR 15) cut the XOR *count* 30-60%, but below
// ~2 KiB regions the host tier is bound by one numpy dispatch per
// XOR, not by XOR work (ROADMAP item 2; the ISA-L-class endgame of
// arXiv:2108.02692 is to compile the whole schedule into ONE fused
// region pass).  This file is that pass: ec/xsched.py lowers a
// schedule once into a flat int32 op tape over a uniform region
// arena, and the entire program — every temp, every output row, for
// N packed objects — runs in a single Python->native transition with
// word-wide unrolled XOR loops.
//
// Region arena: (n_objects, n_regions, region_bytes) contiguous
// uint8.  Per object the region index space is the schedule's:
// [0, n_in) input columns, [n_in, n_in+n_slots) reusable temp slots,
// [n_in+n_slots, n_regions) output rows.  The same tape replays for
// every object (cross-OBJECT batching: thousands of 4 KiB objects
// are one call).
//
// Op encoding — int32 triples (dst, a, b):
//   b >= 0           region[dst] = region[a] ^ region[b]
//   b == -1, a >= 0  region[dst] = region[a]              (copy)
//   b == -2          region[dst] ^= region[a]             (accumulate)
//   a == -1          region[dst] = 0                      (zero fill)
//
// Aliasing: dst may equal a or b EXACTLY (the slot-donation trick the
// scheduler's linear-scan allocator uses); the loops read and write
// element-wise forward, which is well-defined for exact aliasing.

#include <cstdint>
#include <cstring>

extern "C" {

// from checksum.cc
uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t *data, uint64_t len);

}  // extern "C"

namespace {

inline void xor2(uint8_t *d, const uint8_t *a, const uint8_t *b,
                 uint64_t n) {
    uint64_t i = 0;
    for (; i + 32 <= n; i += 32) {
        uint64_t a0, a1, a2, a3, b0, b1, b2, b3;
        std::memcpy(&a0, a + i, 8);
        std::memcpy(&a1, a + i + 8, 8);
        std::memcpy(&a2, a + i + 16, 8);
        std::memcpy(&a3, a + i + 24, 8);
        std::memcpy(&b0, b + i, 8);
        std::memcpy(&b1, b + i + 8, 8);
        std::memcpy(&b2, b + i + 16, 8);
        std::memcpy(&b3, b + i + 24, 8);
        a0 ^= b0; a1 ^= b1; a2 ^= b2; a3 ^= b3;
        std::memcpy(d + i, &a0, 8);
        std::memcpy(d + i + 8, &a1, 8);
        std::memcpy(d + i + 16, &a2, 8);
        std::memcpy(d + i + 24, &a3, 8);
    }
    for (; i + 8 <= n; i += 8) {
        uint64_t x, y;
        std::memcpy(&x, a + i, 8);
        std::memcpy(&y, b + i, 8);
        x ^= y;
        std::memcpy(d + i, &x, 8);
    }
    for (; i < n; ++i) d[i] = a[i] ^ b[i];
}

inline void xacc(uint8_t *d, const uint8_t *a, uint64_t n) {
    xor2(d, d, a, n);
}

}  // namespace

extern "C" {

// Run the whole op tape over every object of the arena: ONE call per
// batch, zero per-XOR dispatch cost.  `tape` is (n_ops, 3) int32 in
// the encoding above; `arena` is (n_objects, n_regions, rbytes)
// contiguous.  Refs are validated by the Python lowering (the tape is
// memoized next to the schedule it was lowered from), not re-checked
// per op here.
void ceph_tpu_xsched_exec(const int32_t *tape, uint64_t n_ops,
                          uint8_t *arena, uint64_t n_regions,
                          uint64_t rbytes, uint64_t n_objects) {
    for (uint64_t o = 0; o < n_objects; ++o) {
        uint8_t *base = arena + o * n_regions * rbytes;
        const int32_t *op = tape;
        for (uint64_t t = 0; t < n_ops; ++t, op += 3) {
            const int32_t dst = op[0], a = op[1], b = op[2];
            uint8_t *d = base + (uint64_t)dst * rbytes;
            if (b >= 0) {
                xor2(d, base + (uint64_t)a * rbytes,
                     base + (uint64_t)b * rbytes, rbytes);
            } else if (b == -2) {
                xacc(d, base + (uint64_t)a * rbytes, rbytes);
            } else if (a >= 0) {
                if (d != base + (uint64_t)a * rbytes)
                    std::memcpy(d, base + (uint64_t)a * rbytes,
                                rbytes);
            } else {
                std::memset(d, 0, rbytes);
            }
        }
    }
}

// Per-shard cumulative crc32c over contiguous region spans of the
// SAME arena the tape just ran over — the HashInfo ledger of a packed
// multi-object encode batch without one Python crc call per shard per
// stripe.  `spans` is (n_spans, 3) int32 rows (region_start, count,
// crc_slot), region_start indexed over the FLAT arena (object-major,
// exactly how the packer laid regions out); each span folds
// count*rbytes bytes into crcs[crc_slot] in order, so multi-stripe
// shards accumulate stripe by stripe like HashInfo::append.
void ceph_tpu_xsched_crc_spans(const uint8_t *arena, uint64_t rbytes,
                               const int32_t *spans, uint64_t n_spans,
                               uint32_t *crcs) {
    const int32_t *s = spans;
    for (uint64_t i = 0; i < n_spans; ++i, s += 3) {
        const uint64_t start = (uint64_t)s[0];
        const uint64_t len = (uint64_t)s[1] * rbytes;
        crcs[s[2]] = ceph_tpu_crc32c(crcs[s[2]],
                                     arena + start * rbytes, len);
    }
}

}  // extern "C"

// Fused host datapath helpers: the per-byte passes of an EC object
// write collapsed into one native call.
//
// Reference parity: the reference's write path stacks independent
// native passes — bufferlist rebuild/alignment (src/common/buffer.cc
// rebuild_aligned_size_and_memory), jerasure/isa-l region encode
// (src/erasure-code/), per-shard cumulative crc32c for HashInfo
// (src/osd/ECUtil.h:101-160, crc asm in src/common/crc32c_intel_fast.c)
// — each a separate C++ loop over the data.  Here the GF(2^8) parity
// accumulate, the per-shard hinfo crcs and the logical content digest
// run chunk-by-chunk in ONE cache-resident pass (and one
// Python->native transition), and the data shards are never copied at
// all — the store adopts strided views (common/buffer.py StridedBuf).
//
// The TPU path replaces the matmul pass with the batched Pallas words
// kernel (ops/gf_pallas.py); this file is the host tier the empirical
// dispatch gate races it against (ec/dispatch.py).

#include <cstdint>
#include <cstring>

extern "C" {

// from checksum.cc
uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t *data, uint64_t len);

// declared in gf_simd.cc
void ceph_tpu_gf_region_mad_v(uint8_t *dst, const uint8_t *src,
                              uint64_t len, const uint8_t *tbl);
void ceph_tpu_gf_region_mul_v(uint8_t *dst, const uint8_t *src,
                              uint64_t len, const uint8_t *tbl);

// Transpose-free whole-object encode, one cache-resident pass:
//   src         (n_stripes, k, chunk) logical object bytes
//   parity_out  (m, n_stripes*chunk)  per-shard parity streams
//   crc_inout   k+m seeds -> cumulative per-shard crc32c (may be null)
//   logical_len unpadded byte count of src; *logical_crc_inout (may be
//               null) accumulates crc32c over src[:logical_len] — the
//               content digest the write reply carries back so the
//               gateway never re-reads the object for its ETag (the
//               librados returnvec role, osd_types.h OSDOp::outdata).
// The k data shards are NOT copied: callers hand the store strided
// views of src (shard i = src[:, i, :]) — the bufferlist
// share-don't-copy discipline; on a low-memory-bandwidth host the
// eliminated 2x object-size of transpose traffic is the difference.
// Column 0 uses the non-accumulating mul so the parity buffers need no
// memset pass.  Per 4 KiB chunk everything (parity mads, crcs) runs
// while the chunk is L1/L2-hot, so total memory traffic is
// read(object) + write(parity).
void ceph_tpu_ec_encode_noT(const uint8_t *mat_tables, uint64_t m,
                            uint64_t k, const uint8_t *src,
                            uint64_t n_stripes, uint64_t chunk,
                            uint8_t *parity_out, uint32_t *crc_inout,
                            uint64_t logical_len,
                            uint32_t *logical_crc_inout) {
  const uint64_t stream = n_stripes * chunk;
  uint64_t remaining = logical_len;
  for (uint64_t s = 0; s < n_stripes; s++) {
    const uint8_t *row = src + s * k * chunk;
    for (uint64_t i = 0; i < k; i++) {
      const uint8_t *d = row + i * chunk;
      for (uint64_t j = 0; j < m; j++) {
        const uint8_t *tbl = mat_tables + (j * k + i) * 256;
        uint8_t *dst = parity_out + j * stream + s * chunk;
        if (i == 0)
          ceph_tpu_gf_region_mul_v(dst, d, chunk, tbl);
        else
          ceph_tpu_gf_region_mad_v(dst, d, chunk, tbl);
      }
      if (crc_inout != nullptr)
        crc_inout[i] = ceph_tpu_crc32c(crc_inout[i], d, chunk);
      if (logical_crc_inout != nullptr && remaining > 0) {
        uint64_t take = remaining < chunk ? remaining : chunk;
        *logical_crc_inout = ceph_tpu_crc32c(*logical_crc_inout, d, take);
        remaining -= take;
      }
    }
    if (crc_inout != nullptr)
      for (uint64_t j = 0; j < m; j++)
        crc_inout[k + j] = ceph_tpu_crc32c(
            crc_inout[k + j], parity_out + j * stream + s * chunk, chunk);
  }
}

}  // extern "C"

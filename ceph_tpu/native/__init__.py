"""Native (C++) runtime for ceph_tpu — build-on-demand ctypes bindings.

The reference ships its host-side hot loops as C/C++/asm (crc32c asm,
xxHash, jerasure/isa-l region ops).  ceph_tpu keeps the same split: bulk
data-path math runs on TPU via JAX, while the host runtime (checksums for
metadata, GF region fallback, per-block csum loops) is native C++ compiled
here with g++ at first import and loaded through ctypes.

Sources live in ceph_tpu/native/src/; the shared object is cached next to
them keyed by a source hash, so rebuilds happen only when sources change.
If no compiler is available the pure-python fallbacks in ceph_tpu.ops keep
everything functional (slower).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "build")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc")
    )


def _source_hash() -> str:
    h = hashlib.sha256()
    for path in _sources():
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"libceph_tpu_native-{_source_hash()}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", so_path + ".tmp", *_sources(),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(so_path + ".tmp", so_path)
    return so_path


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32, u64 = ctypes.c_uint32, ctypes.c_uint64

    lib.ceph_tpu_crc32c.restype = u32
    lib.ceph_tpu_crc32c.argtypes = [u32, u8p, u64]
    lib.ceph_tpu_crc32c_zeros.restype = u32
    lib.ceph_tpu_crc32c_zeros.argtypes = [u32, u64]
    lib.ceph_tpu_crc32c_combine.restype = u32
    lib.ceph_tpu_crc32c_combine.argtypes = [u32, u32, u64]
    lib.ceph_tpu_crc32c_blocks.restype = None
    lib.ceph_tpu_crc32c_blocks.argtypes = [u8p, u64, u64, u32, u32p]
    lib.ceph_tpu_xxh32.restype = u32
    lib.ceph_tpu_xxh32.argtypes = [u8p, u64, u32]
    lib.ceph_tpu_xxh64.restype = u64
    lib.ceph_tpu_xxh64.argtypes = [u8p, u64, u64]
    lib.ceph_tpu_xxh32_blocks.restype = None
    lib.ceph_tpu_xxh32_blocks.argtypes = [u8p, u64, u64, u32, u32p]
    lib.ceph_tpu_xxh64_blocks.restype = None
    lib.ceph_tpu_xxh64_blocks.argtypes = [u8p, u64, u64, u64, u64p]
    lib.ceph_tpu_region_xor.restype = None
    lib.ceph_tpu_region_xor.argtypes = [u8p, u8p, u64]
    lib.ceph_tpu_region_mad.restype = None
    lib.ceph_tpu_region_mad.argtypes = [u8p, u8p, u64, u8p]
    lib.ceph_tpu_gf_matmul.restype = None
    lib.ceph_tpu_gf_matmul.argtypes = [u8p, u64, u64, u8p, u64, u8p]
    try:  # SIMD GF tier (gf_simd.cc) — optional on stale .so
        lib.ceph_tpu_gf_simd_level.restype = ctypes.c_int
        lib.ceph_tpu_gf_simd_level.argtypes = []
        lib.ceph_tpu_gf_region_mad_v.restype = None
        lib.ceph_tpu_gf_region_mad_v.argtypes = [u8p, u8p, u64, u8p]
        lib.ceph_tpu_gf_matmul_simd.restype = None
        lib.ceph_tpu_gf_matmul_simd.argtypes = [u8p, u64, u64, u8p,
                                                u64, u8p]
    except AttributeError:
        pass
    try:  # compression codecs are an optional capability of the library
        i64 = ctypes.c_int64
        for alg in ("lz4", "snappy"):
            bound = getattr(lib, f"ceph_tpu_{alg}_compress_bound")
            bound.restype = u64
            bound.argtypes = [u64]
            for op in ("compress", "decompress"):
                fn = getattr(lib, f"ceph_tpu_{alg}_{op}")
                fn.restype = i64
                fn.argtypes = [u8p, u64, u8p, u64]
        lib.ceph_tpu_snappy_uncompressed_length.restype = i64
        lib.ceph_tpu_snappy_uncompressed_length.argtypes = [u8p, u64]
    except AttributeError:  # stale .so without compress.cc
        pass
    try:
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.ceph_tpu_ec_encode_noT.restype = None
        lib.ceph_tpu_ec_encode_noT.argtypes = [
            u8p, u64, u64, u8p, u64, u64, u8p, u32p, u64, u32p]
    except AttributeError:  # stale .so without datapath.cc
        pass
    try:  # fused XOR-schedule executor (xor_sched.cc)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ceph_tpu_xsched_exec.restype = None
        lib.ceph_tpu_xsched_exec.argtypes = [i32p, u64, u8p, u64, u64,
                                             u64]
        lib.ceph_tpu_xsched_crc_spans.restype = None
        lib.ceph_tpu_xsched_crc_spans.argtypes = [u8p, u64, i32p, u64,
                                                  u32p]
    except AttributeError:  # stale .so without xor_sched.cc
        pass
    try:  # AEAD (aesgcm.cc) — msgr2 secure mode
        for op in ("seal", "open"):
            fn = getattr(lib, f"ceph_tpu_aesgcm_{op}")
            fn.restype = ctypes.c_int
            fn.argtypes = [u8p, u8p, u8p, u64, u8p, u64, u8p]
    except AttributeError:  # stale .so without aesgcm.cc
        pass
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unbuildable."""
    global _lib, _build_error
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            _lib = _bind(ctypes.CDLL(_build()))
            _tune_allocator()
        except Exception as e:  # pragma: no cover - only on broken toolchain
            _build_error = str(e)
            _lib = None
    return _lib


def prewarmed() -> bool:
    """True once get_lib() has resolved (built or failed for good):
    callers that prewarm the one-shot build off-loop can skip the
    thread hop on every later check."""
    return _lib is not None or _build_error is not None


def _tune_allocator() -> None:
    """Keep multi-MiB data-path buffers on the recycled heap.

    glibc serves large mallocs with fresh mmaps and unmaps them on
    free, so every encode's stripe/parity arenas pay page-fault +
    zero-fill for all their pages — a measured ~3x slowdown of the
    fused encode on the bench host.  The reference links tcmalloc for
    exactly this reason (do_cmake.sh ALLOCATOR=tcmalloc; perfglue/).
    mallopt(M_MMAP_THRESHOLD) is the glibc-native equivalent: large
    blocks come from the main heap and are reused across ops.
    """
    try:
        libc = ctypes.CDLL(None)
        M_MMAP_THRESHOLD = -3
        libc.mallopt(M_MMAP_THRESHOLD, 256 << 20)
    except Exception:  # non-glibc platform: harmless to skip
        pass


def build_error() -> Optional[str]:
    return _build_error

"""prometheus module: /metrics exposition endpoint.

Reference parity: /root/reference/src/pybind/mgr/prometheus/module.py —
an HTTP endpoint serving cluster health, OSD up/in state, pool
metadata, per-daemon perf counters in the Prometheus text exposition
format.  The reference runs cherrypy; here a minimal asyncio HTTP/1.0
responder (GET-only) is plenty and keeps the daemon dependency-free.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ceph_tpu.mgr import MgrModule

log = logging.getLogger("mgr")


def _esc(value) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(name: str, value, labels: Optional[Dict[str, Any]] = None
         ) -> str:
    if labels:
        inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


class PrometheusModule(MgrModule):
    NAME = "prometheus"

    def __init__(self, mgr, port: int = 0):
        super().__init__(mgr)
        self.port = int(mgr.config.get("prometheus_port", port))
        self.addr: Optional[str] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", self.port)
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"
        log.info("mgr: prometheus exporter on %s", self.addr)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 5.0)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 else None
            if path in ("/", "/metrics", "/metrics/"):
                body = await self.collect()
                status = "200 OK"
            elif path is None:
                body, status = "bad request\n", "400 Bad Request"
            else:
                body, status = "not found\n", "404 Not Found"
            payload = body.encode()
            writer.write(
                f"HTTP/1.0 {status}\r\n"
                f"Content-Type: text/plain; version=0.0.4\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def collect(self) -> str:
        """One exposition document from the subscribed map + scrapes."""
        lines: List[str] = []
        osdmap = self.mgr.osdmap
        if osdmap is None:
            return "# cluster map not yet received\n"
        lines.append("# TYPE ceph_osdmap_epoch gauge")
        lines.append(_fmt("ceph_osdmap_epoch", osdmap.epoch))
        lines.append("# TYPE ceph_osd_up gauge")
        lines.append("# TYPE ceph_osd_in gauge")
        for o in range(osdmap.max_osd):
            if not osdmap.exists(o):
                continue
            labels = {"ceph_daemon": f"osd.{o}"}
            lines.append(_fmt("ceph_osd_up",
                              int(osdmap.is_up(o)), labels))
            lines.append(_fmt("ceph_osd_in",
                              int(osdmap.is_in(o)), labels))
        lines.append("# TYPE ceph_pool_pg_num gauge")
        for pool in osdmap.pools.values():
            lines.append(_fmt("ceph_pool_pg_num", pool.pg_num,
                              {"pool": pool.name}))
        lines.append("# TYPE ceph_pg_per_osd gauge")
        for o, n in self.mgr.pgs_per_osd().items():
            lines.append(_fmt("ceph_pg_per_osd", n,
                              {"ceph_daemon": f"osd.{o}"}))
        # autoscaler recommendations ride along when the module is up
        scaler = self.mgr.modules.get("pg_autoscaler")
        if scaler is not None:
            lines.append(
                "# TYPE ceph_pool_recommended_pg_num gauge")
            for row in scaler.compute().values():
                lines.append(_fmt("ceph_pool_recommended_pg_num",
                                  row["pg_num_ideal"],
                                  {"pool": row["pool_name"]}))
        # per-OSD perf counters over the tell surface
        perf = await self.mgr.scrape_osd_perf()
        seen_types = set()
        for o, counters in sorted(perf.items()):
            for key, value in sorted(counters.items()):
                if not isinstance(value, (int, float)):
                    continue
                metric = f"ceph_osd_{key}"
                if metric not in seen_types:
                    lines.append(f"# TYPE {metric} counter")
                    seen_types.add(metric)
                lines.append(_fmt(metric, value,
                                  {"ceph_daemon": f"osd.{o}"}))
        # mon health
        try:
            rc, health = await self.mgr.client.mon_command(
                {"prefix": "health"})
            if rc == 0:
                lines.append("# TYPE ceph_health_status gauge")
                lines.append(_fmt(
                    "ceph_health_status",
                    0 if health.get("status") == "HEALTH_OK" else 1))
        except Exception:
            pass
        return "\n".join(lines) + "\n"

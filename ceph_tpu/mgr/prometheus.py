"""prometheus module: /metrics exposition endpoint.

Reference parity: /root/reference/src/pybind/mgr/prometheus/module.py —
an HTTP endpoint serving cluster health, OSD up/in state, pool
metadata, per-daemon perf counters in the Prometheus text exposition
format.  The reference runs cherrypy; here a minimal asyncio HTTP/1.0
responder (GET-only) is plenty and keeps the daemon dependency-free.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ceph_tpu.mgr import MgrModule

log = logging.getLogger("mgr")


def _esc(value) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(name: str, value, labels: Optional[Dict[str, Any]] = None
         ) -> str:
    if labels:
        inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


class PrometheusModule(MgrModule):
    NAME = "prometheus"

    def __init__(self, mgr, port: int = 0):
        super().__init__(mgr)
        self.port = int(mgr.config.get("prometheus_port", port))
        self.addr: Optional[str] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", self.port)
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"
        log.info("mgr: prometheus exporter on %s", self.addr)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 5.0)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 else None
            if path in ("/", "/metrics", "/metrics/"):
                body = await self.collect()
                status = "200 OK"
            elif path is None:
                body, status = "bad request\n", "400 Bad Request"
            else:
                body, status = "not found\n", "404 Not Found"
            payload = body.encode()
            writer.write(
                f"HTTP/1.0 {status}\r\n"
                f"Content-Type: text/plain; version=0.0.4\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    @staticmethod
    def _safe_name(name: str) -> str:
        """Metric-name charset: [a-zA-Z0-9_:]; everything else -> _."""
        return "".join(c if c.isalnum() or c in "_:" else "_"
                       for c in name)

    # perf-dump leaves that are LEVELS, not monotone counts: declaring
    # them counters would make rate()/increase() read every decrease
    # as a counter reset.  Matched on the flattened metric's suffix.
    _GAUGE_SUFFIXES = (
        "_cached_bytes", "_cached_objects", "_inflight",
        "_queue_depth", "_queue_bytes", "_window_ms",
        "_max_batch_bytes", "_enabled", "_plans",
        # device-health breaker leaves: state and backoff are levels,
        # and the consecutive-failure count resets on every success;
        # a chip's mesh membership is a level too
        "_state_code", "_retry_in_s", "_consecutive",
        "_quarantined_plans", "_mesh_member",
        # hedge per-peer latency model leaves: moving estimates, not
        # monotone counts
        "_ewma_ms", "_p95_ms",
        # QoS leaves: queue occupancy, grant concurrency, bucket
        # levels and the configured bounds are all levels
        "_in_flight", "_queued", "_max_concurrent",
        "_max_queue_depth", "_tokens", "_limit_ops",
        # tracing leaves: percentile estimates, the sampling knob and
        # the exemplar-ring occupancy are levels, not monotone counts
        "_p50_ms", "_p99_ms", "_sample_rate", "_exemplars_held",
        "_complaint_time_s",
    )

    # nested maps that become a LABEL instead of exploding the metric
    # namespace: map-key suffix -> (metric tail, label name)
    _LABEL_MAPS = {
        "profiles": ("profile", "profile"),
        "per_plan": ("profile", "profile"),
        # the hedge section's per-peer EWMA/breaker model
        "peers": ("peer", "peer"),
        # the qos section's per-tenant admission/queue rows
        "tenants": ("tenant", "tenant"),
        # the device-health section's per-chip breaker + mesh rows
        "devices": ("device", "device"),
        # the trace section's per-stage critical-path self-time rows
        # (ceph_osd_trace_stage_self_seconds_bucket{stage=...})
        "stage": ("stage", "stage"),
    }

    @classmethod
    def _emit_perf(cls, lines: List[str], seen_types: set,
                   metric: str, value,
                   labels: Dict[str, Any]) -> None:
        """One perf-dump entry -> exposition lines.

        - numeric/bool: plain counter sample;
        - PerfCounters histogram dump ({buckets, bounds, count, sum}):
          cumulative `_bucket{le=...}` rows + `_count`/`_sum`;
        - a `profiles`/`per_plan`/`peers`/`tenants`/`devices` map:
          recurse with a `profile`/`peer`/`tenant`/`device` label
          instead of exploding the metric namespace (_LABEL_MAPS);
        - any other dict: recurse with _-joined names (the tier /
          plan_cache / encode_service sections).
        Non-numeric leaves (strings, lists) are skipped."""
        metric = cls._safe_name(metric)
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, (int, float)):
            if metric not in seen_types:
                kind = "gauge" if metric.endswith(
                    cls._GAUGE_SUFFIXES) else "counter"
                lines.append(f"# TYPE {metric} {kind}")
                seen_types.add(metric)
            lines.append(_fmt(metric, value, labels))
            return
        if not isinstance(value, dict):
            return
        if "buckets" in value and "bounds" in value:
            if metric not in seen_types:
                lines.append(f"# TYPE {metric} histogram")
                seen_types.add(metric)
            cum = 0
            for bound, n in zip(value["bounds"], value["buckets"]):
                cum += n
                lines.append(_fmt(f"{metric}_bucket", cum,
                                  {**labels, "le": bound}))
            cum += value["buckets"][-1] if len(value["buckets"]) > \
                len(value["bounds"]) else 0
            lines.append(_fmt(f"{metric}_bucket", cum,
                              {**labels, "le": "+Inf"}))
            lines.append(_fmt(f"{metric}_count",
                              value.get("count", cum), labels))
            lines.append(_fmt(f"{metric}_sum", value.get("sum", 0),
                              labels))
            return
        for special, (tail, label) in cls._LABEL_MAPS.items():
            suffix = "_" + special
            if not metric.endswith(suffix):
                continue
            base = metric[:-len(suffix)] + "_" + tail
            for profile, stats in sorted(value.items()):
                if not isinstance(stats, dict):
                    continue
                plabels = {**labels, label: profile}
                for k, v in sorted(stats.items()):
                    cls._emit_perf(lines, seen_types, f"{base}_{k}",
                                   v, plabels)
            return
        for k, v in sorted(value.items()):
            cls._emit_perf(lines, seen_types, f"{metric}_{k}", v,
                           labels)

    async def collect(self) -> str:
        """One exposition document from the subscribed map + scrapes."""
        lines: List[str] = []
        osdmap = self.mgr.osdmap
        if osdmap is None:
            return "# cluster map not yet received\n"
        lines.append("# TYPE ceph_osdmap_epoch gauge")
        lines.append(_fmt("ceph_osdmap_epoch", osdmap.epoch))
        lines.append("# TYPE ceph_osd_up gauge")
        lines.append("# TYPE ceph_osd_in gauge")
        for o in range(osdmap.max_osd):
            if not osdmap.exists(o):
                continue
            labels = {"ceph_daemon": f"osd.{o}"}
            lines.append(_fmt("ceph_osd_up",
                              int(osdmap.is_up(o)), labels))
            lines.append(_fmt("ceph_osd_in",
                              int(osdmap.is_in(o)), labels))
        lines.append("# TYPE ceph_pool_pg_num gauge")
        for pool in osdmap.pools.values():
            lines.append(_fmt("ceph_pool_pg_num", pool.pg_num,
                              {"pool": pool.name}))
        lines.append("# TYPE ceph_pg_per_osd gauge")
        for o, n in self.mgr.pgs_per_osd().items():
            lines.append(_fmt("ceph_pg_per_osd", n,
                              {"ceph_daemon": f"osd.{o}"}))
        # autoscaler recommendations ride along when the module is up
        scaler = self.mgr.modules.get("pg_autoscaler")
        if scaler is not None:
            lines.append(
                "# TYPE ceph_pool_recommended_pg_num gauge")
            for row in scaler.compute().values():
                lines.append(_fmt("ceph_pool_recommended_pg_num",
                                  row["pg_num_ideal"],
                                  {"pool": row["pool_name"]}))
        # per-OSD perf counters over the tell surface.  The dump is
        # nested since the tier/plan-cache/encode-service sections
        # landed: scalars flatten with _-joined names, per-profile
        # maps become `profile` labels, histogram dicts export as
        # prometheus histograms (read-frequency rows etc.)
        perf = await self.mgr.scrape_osd_perf()
        seen_types = set()
        for o, counters in sorted(perf.items()):
            labels = {"ceph_daemon": f"osd.{o}"}
            for key, value in sorted(counters.items()):
                self._emit_perf(lines, seen_types, f"ceph_osd_{key}",
                                value, labels)
        # mon health (emitted after the perf walk)
        try:
            rc, health = await self.mgr.client.mon_command(
                {"prefix": "health"})
            if rc == 0:
                lines.append("# TYPE ceph_health_status gauge")
                lines.append(_fmt(
                    "ceph_health_status",
                    0 if health.get("status") == "HEALTH_OK" else 1))
        except Exception:
            pass
        return "\n".join(lines) + "\n"

"""telemetry module: periodic anonymized cluster report.

Reference parity: /root/reference/src/pybind/mgr/telemetry/module.py —
collects an anonymized snapshot of cluster composition and health
(counts, versions, pool shapes — never object names or user data) on
an interval.  The reference POSTs it to telemetry.ceph.com; this
build has zero egress by design, so the report lands in a rados
object (`mgr_telemetry_report` in the first pool) and is served over
the module's surface (`report()`), which covers the operational role:
an operator (or a support bundle) reads one JSON document describing
the cluster.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, Optional

from ceph_tpu.mgr import MgrModule

log = logging.getLogger("mgr")

REPORT_OBJ = "mgr_telemetry_report"


class TelemetryModule(MgrModule):
    NAME = "telemetry"

    def __init__(self, mgr, interval: float = 60.0):
        super().__init__(mgr)
        self.interval = float(mgr.config.get("telemetry_interval",
                                             interval))
        self.last_report: Optional[Dict[str, Any]] = None
        self._last_t = 0.0

    async def serve_once(self) -> None:
        if time.monotonic() - self._last_t < self.interval:
            return
        self._last_t = time.monotonic()
        try:
            await self.compile_and_store()
        except Exception:
            log.exception("mgr: telemetry collection failed")

    async def report(self) -> Dict[str, Any]:
        """One anonymized cluster snapshot (collected fresh)."""
        osdmap = self.mgr.osdmap
        doc: Dict[str, Any] = {"ts": time.time(),
                               "channel": "basic"}
        if osdmap is None:
            return doc
        up = osdmap.get_up_osds()
        doc["osd"] = {
            "count": sum(1 for o in range(osdmap.max_osd)
                         if osdmap.exists(o)),
            "up": len(up),
            "in": sum(1 for o in range(osdmap.max_osd)
                      if osdmap.is_in(o)),
        }
        # pool SHAPES only — names are user data and stay out, like
        # the reference's anonymization
        pools = []
        for p in osdmap.pools.values():
            profile = osdmap.erasure_code_profiles.get(
                p.erasure_code_profile, {})
            pools.append(
                {"type": "erasure" if p.is_erasure()
                 else "replicated",
                 "size": p.size, "pg_num": p.pg_num,
                 "ec_profile": {k: v for k, v in profile.items()
                                if k in ("plugin", "technique", "k",
                                         "m", "l", "d")}})
        doc["pools"] = pools
        doc["epoch"] = osdmap.epoch
        try:
            rc, health = await self.mgr.client.mon_command(
                {"prefix": "health"})
            if rc == 0:
                doc["health"] = {
                    "status": health.get("status"),
                    "checks": sorted(health.get("checks", {}))}
        except Exception:
            pass
        try:
            rc, stat = await self.mgr.client.mon_command(
                {"prefix": "mon stat"})
            if rc == 0:
                doc["mon"] = {"count": stat.get("num_mons", 1),
                              "quorum": len(stat.get("quorum", []))
                              or 1}
        except Exception:
            pass
        return doc

    async def compile_and_store(self) -> Dict[str, Any]:
        doc = await self.report()
        self.last_report = doc
        # persist into the first pool (support-bundle pickup point)
        osdmap = self.mgr.osdmap
        if osdmap is not None and osdmap.pools:
            from ceph_tpu.rados.client import IoCtx

            pool_id = sorted(osdmap.pools)[0]
            io = IoCtx(self.mgr.client, pool_id)
            try:
                await io.write_full(REPORT_OBJ,
                                    json.dumps(doc).encode())
            except Exception:
                pass  # a degraded pool must not kill the tick
        return doc

"""MGR role: cluster-wide aggregation + management modules.

Reference parity: the ceph-mgr daemon (/root/reference/src/mgr/ —
MgrStandby/Mgr/DaemonServer) hosting python modules under
/root/reference/src/pybind/mgr/ (balancer, pg_autoscaler, prometheus).
The reference mgr receives daemon perf reports over its own messenger
and exposes module surfaces; here the mgr is a CLIENT of the cluster —
it subscribes to maps like any rados client and scrapes per-OSD state
over the MOSDCommand wire surface (`ceph tell` role), which the mini-mon
architecture makes equivalent and far simpler: no second server-side
report path to keep consistent.

Modules follow the pybind/mgr shape: a registry of named module
instances, each driven by a periodic serve tick, reading cluster state
through the hosting daemon and acting through mon/osd commands.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.osd.osdmap import OSDMap, PgId
from ceph_tpu.rados.client import RadosClient

log = logging.getLogger("mgr")


class MgrModule:
    """Base for mgr modules (pybind/mgr MgrModule role)."""

    NAME = ""

    def __init__(self, mgr: "MgrDaemon"):
        self.mgr = mgr

    async def serve_once(self) -> None:
        """One periodic tick; modules do their work here."""

    async def start(self) -> None:
        """Module bring-up (servers, sockets)."""

    async def stop(self) -> None:
        """Module teardown."""


class MgrDaemon:
    """Hosts mgr modules over a rados client connection.

    `modules` selects which modules run (names); None = all built-in
    (balancer runs in manual mode — see its `active` flag — matching
    the reference default of `balancer mode none`).
    """

    def __init__(self, mon_addr: str,
                 modules: Optional[List[str]] = None,
                 tick_interval: float = 1.0,
                 config: Optional[Dict[str, Any]] = None):
        self.mon_addr = mon_addr
        self.config = config or {}
        self.tick_interval = tick_interval
        self.client = RadosClient(
            mon_addr, name="mgr.x",
            secret=self.config.get("auth_secret"),
            secure=bool(self.config.get("auth_secure")))
        self.modules: Dict[str, MgrModule] = {}
        self._module_filter = modules
        self._tick_task: Optional[asyncio.Task] = None
        self._stopping = False

    @property
    def osdmap(self) -> Optional[OSDMap]:
        return self.client.osdmap

    async def start(self) -> None:
        from ceph_tpu.mgr.balancer import BalancerModule
        from ceph_tpu.mgr.dashboard import DashboardModule
        from ceph_tpu.mgr.pg_autoscaler import PgAutoscalerModule
        from ceph_tpu.mgr.prometheus import PrometheusModule
        from ceph_tpu.mgr.rbd_support import RbdSupportModule
        from ceph_tpu.mgr.telemetry import TelemetryModule

        await self.client.connect()
        for cls in (BalancerModule, PgAutoscalerModule,
                    PrometheusModule, DashboardModule,
                    TelemetryModule, RbdSupportModule):
            if self._module_filter is not None and \
                    cls.NAME not in self._module_filter:
                continue
            mod = cls(self)
            self.modules[cls.NAME] = mod
            await mod.start()
        self._tick_task = asyncio.get_running_loop().create_task(
            self._tick_loop())

    async def stop(self) -> None:
        self._stopping = True
        if self._tick_task is not None:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except asyncio.CancelledError:
                pass
        for mod in self.modules.values():
            await mod.stop()
        await self.client.shutdown()

    async def _tick_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.tick_interval)
            for name, mod in list(self.modules.items()):
                try:
                    await mod.serve_once()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("mgr: module %s tick failed", name)

    # -- shared cluster-state helpers (modules read through these) --------

    def pg_mappings(self, pool_id: int) -> Dict[PgId, List[int]]:
        """pg -> up osd set for one pool, from the subscribed map."""
        osdmap = self.osdmap
        out: Dict[PgId, List[int]] = {}
        if osdmap is None:
            return out
        pool = osdmap.pools.get(pool_id)
        if pool is None:
            return out
        for ps in range(pool.pg_num):
            pg = PgId(pool_id, ps)
            up, _primary = osdmap.pg_to_acting_osds(pg)
            out[pg] = [o for o in up if o >= 0]
        return out

    def pgs_per_osd(self, pool_id: Optional[int] = None
                    ) -> Dict[int, int]:
        """PG replica count per OSD (one pool or all pools)."""
        osdmap = self.osdmap
        counts: Dict[int, int] = {}
        if osdmap is None:
            return counts
        for o in range(osdmap.max_osd):
            if osdmap.exists(o) and osdmap.is_in(o):
                counts[o] = 0
        pools = ([pool_id] if pool_id is not None
                 else list(osdmap.pools))
        for pid in pools:
            for _pg, osds in self.pg_mappings(pid).items():
                for o in osds:
                    if o in counts:
                        counts[o] += 1
        return counts

    async def scrape_osd_perf(self) -> Dict[int, Dict[str, Any]]:
        """perf counters from every up OSD via `tell` commands."""
        osdmap = self.osdmap
        out: Dict[int, Dict[str, Any]] = {}
        if osdmap is None:
            return out

        async def one(osd: int) -> None:
            try:
                rc, perf = await self.client.osd_command(
                    osd, {"prefix": "perf dump"})
                if rc == 0:
                    out[osd] = perf
            except Exception:
                pass  # a dead/slow OSD just has no row this scrape

        await asyncio.gather(*(one(o) for o in osdmap.get_up_osds()))
        return out

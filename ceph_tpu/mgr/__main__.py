"""Run a mgr as a real process: python -m ceph_tpu.mgr

Prints `MGR_PROMETHEUS <host:port>` once the exporter is bound (the
ceph-helpers run_mgr contract analog).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.mgr import MgrDaemon


async def _main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mon-addr", type=str, required=True)
    ap.add_argument("--modules", type=str, default="",
                    help="comma list; empty = all built-in")
    ap.add_argument("--config", type=str, default="{}",
                    help="JSON mgr config overrides (balancer_active,"
                         " prometheus_port, upmap_max_deviation, ...)")
    args = ap.parse_args()
    modules = [m for m in args.modules.split(",") if m] or None
    mgr = MgrDaemon(args.mon_addr, modules=modules,
                    config=json.loads(args.config))
    await mgr.start()
    prom = mgr.modules.get("prometheus")
    if prom is not None:
        print(f"MGR_PROMETHEUS {prom.addr}", flush=True)
    else:
        print("MGR_UP", flush=True)
    try:
        await asyncio.Event().wait()  # serve until killed
    finally:
        await mgr.stop()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

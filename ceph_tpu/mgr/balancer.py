"""Balancer module: upmap-mode PG distribution smoothing.

Reference parity: /root/reference/src/pybind/mgr/balancer/module.py
(upmap mode) driving OSDMap::calc_pg_upmaps
(/root/reference/src/osd/OSDMap.cc:4737) — compute per-OSD PG counts,
move PGs off overfull OSDs onto underfull ones via pg_upmap_items,
stop when the max deviation from the mean is within tolerance.

The reference's C++ optimizer iterates random perturbations inside the
map; here the greedy equivalent runs over the subscribed map and acts
through the mon's `osd pg-upmap-items` command, so every step is an
ordinary auditable map mutation and daemons re-peer incrementally.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ceph_tpu.mgr import MgrModule
from ceph_tpu.osd.osdmap import PgId

log = logging.getLogger("mgr")


class BalancerModule(MgrModule):
    NAME = "balancer"

    # upmap_max_deviation: the reference default is 5 PGs; small test
    # clusters want 1 (perfect-as-possible balance)
    def __init__(self, mgr, max_deviation: int = 1,
                 max_iterations: int = 64):
        super().__init__(mgr)
        self.max_deviation = int(
            mgr.config.get("upmap_max_deviation", max_deviation))
        self.max_iterations = max_iterations
        # `balancer mode none` by default, like the reference; flip on
        # explicitly (tests / `active = True`) or via mgr config
        self.active = bool(mgr.config.get("balancer_active", False))
        self.last_optimize: Dict[int, int] = {}  # pool -> moves applied

    async def serve_once(self) -> None:
        if not self.active:
            return
        await self.optimize()

    def _eligible_osds(self) -> List[int]:
        osdmap = self.mgr.osdmap
        return [o for o in range(osdmap.max_osd)
                if osdmap.exists(o) and osdmap.is_in(o)
                and osdmap.is_up(o)]

    def plan_pool(self, pool_id: int
                  ) -> List[Tuple[PgId, List[Tuple[int, int]]]]:
        """Greedy calc_pg_upmaps for one pool: list of
        (pg, full pg_upmap_items value) proposals that reduce the
        spread.  Pure planning — nothing is applied."""
        osdmap = self.mgr.osdmap
        if osdmap is None or pool_id not in osdmap.pools:
            return []
        osds = self._eligible_osds()
        if len(osds) < 2:
            return []
        mappings = self.mgr.pg_mappings(pool_id)
        counts: Dict[int, int] = {o: 0 for o in osds}
        for _pg, members in mappings.items():
            for o in members:
                if o in counts:
                    counts[o] += 1
        total = sum(counts.values())
        mean = total / len(osds)
        proposals: List[Tuple[PgId, List[Tuple[int, int]]]] = []
        # working copy of existing explicit remaps so proposals compose
        items: Dict[PgId, List[Tuple[int, int]]] = {
            pg: list(v) for pg, v in osdmap.pg_upmap_items.items()}
        for _round in range(self.max_iterations):
            over = max(counts, key=lambda o: counts[o])
            under = min(counts, key=lambda o: counts[o])
            if counts[over] - mean <= self.max_deviation and \
                    mean - counts[under] <= self.max_deviation:
                break
            moved = False
            for pg, members in mappings.items():
                if over not in members or under in members:
                    continue
                cur = items.get(pg, [])
                # never stack a second remap for the same source slot,
                # and drop a remap that the new one would just undo
                # (maybe_remove_pg_upmaps hygiene)
                if any(dst == over for _src, dst in cur):
                    new_items = [(s, under) if d == over else (s, d)
                                 for s, d in cur]
                    new_items = [(s, d) for s, d in new_items
                                 if s != d]
                else:
                    new_items = cur + [(over, under)]
                if not new_items:
                    continue
                items[pg] = new_items
                mappings[pg] = [under if o == over else o
                                for o in members]
                counts[over] -= 1
                counts[under] += 1
                proposals.append((pg, new_items))
                moved = True
                break
            if not moved:
                break  # no movable PG: constraints beat the deviation
        # collapse multiple proposals on one pg to the final value
        final: Dict[PgId, List[Tuple[int, int]]] = {}
        for pg, value in proposals:
            final[pg] = value
        return list(final.items())

    async def optimize(self) -> int:
        """Plan and apply via the mon; returns PG remaps applied."""
        osdmap = self.mgr.osdmap
        if osdmap is None:
            return 0
        applied = 0
        for pool_id in list(osdmap.pools):
            plan = self.plan_pool(pool_id)
            for pg, items in plan:
                rc, _out = await self.mgr.client.mon_command({
                    "prefix": "osd pg-upmap-items",
                    "pgid": f"{pg.pool}.{pg.ps}",
                    "mappings": [[s, d] for s, d in items]})
                if rc == 0:
                    applied += 1
                else:
                    log.warning("balancer: upmap of %s rejected rc=%d",
                                pg, rc)
            self.last_optimize[pool_id] = len(plan)
            if plan:
                # let the new map flow back before planning more pools
                await self.mgr.client.refresh_map()
        return applied

    def eval_pool(self, pool_id: int) -> Dict[str, float]:
        """Distribution score (the `balancer eval` surface): current
        per-OSD count spread for one pool."""
        counts = self.mgr.pgs_per_osd(pool_id)
        if not counts:
            return {"mean": 0.0, "max_deviation": 0.0}
        mean = sum(counts.values()) / len(counts)
        dev = max(abs(c - mean) for c in counts.values())
        return {"mean": mean, "max_deviation": dev,
                "counts": dict(counts)}

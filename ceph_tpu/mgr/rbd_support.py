"""rbd_support module: snapshot schedules + trash purge schedules.

Reference parity: /root/reference/src/pybind/mgr/rbd_support/ — the
mgr module behind `rbd mirror snapshot schedule` and `rbd trash purge
schedule`: schedules are cluster data (not mgr-local state), the
module's serve loop creates timestamped snapshots for scheduled
images (with retention pruning) and sweeps expired trash entries for
scheduled pools.

Schedules live in each rbd pool's `rbd_schedules` object omap:
  snap\\x1f<image>   {"interval": s, "keep": n}   per-image snapshots
  trash\\x1f         {"interval": s}              pool trash purge
Last-run bookkeeping is module-local (a mgr failover just re-runs at
most one interval early — schedules are idempotent)."""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, Tuple

from ceph_tpu.mgr import MgrModule
from ceph_tpu.rados.client import ObjectNotFound, RadosError

log = logging.getLogger("mgr")

SCHEDULES_OID = "rbd_schedules"
SEP = "\x1f"


class RbdSupportModule(MgrModule):
    NAME = "rbd_support"

    # snapshots created by the schedule: rbd_support's timestamp-name
    # shape (scheduled-%Y-%m-%dT%H:%M:%S)
    SNAP_PREFIX = "scheduled-"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._last_run: Dict[Tuple[str, str], float] = {}

    # -- schedule admin (the `rbd ... schedule add/ls/rm` surface) ---------

    @staticmethod
    async def schedule_snapshots(ioctx, image: str, interval: float,
                                 keep: int = 3) -> None:
        await ioctx.omap_set(SCHEDULES_OID, {
            f"snap{SEP}{image}": json.dumps(
                {"interval": interval, "keep": int(keep)}).encode()})

    @staticmethod
    async def schedule_trash_purge(ioctx, interval: float) -> None:
        await ioctx.omap_set(SCHEDULES_OID, {
            f"trash{SEP}": json.dumps(
                {"interval": interval}).encode()})

    @staticmethod
    async def schedule_rm(ioctx, key: str) -> None:
        await ioctx.omap_rm_keys(SCHEDULES_OID, [key])

    @staticmethod
    async def schedule_ls(ioctx) -> Dict[str, Dict[str, Any]]:
        try:
            omap = await ioctx.omap_get(SCHEDULES_OID)
        except ObjectNotFound:
            return {}
        return {k: json.loads(v.decode()) for k, v in omap.items()}

    # -- serve -------------------------------------------------------------

    async def serve_once(self) -> None:
        osdmap = self.mgr.osdmap
        if osdmap is None:
            return
        for pool in list(osdmap.pools.values()):
            try:
                await self._serve_pool(pool.name)
            except (RadosError, ObjectNotFound):
                continue  # pool without schedules / transient

    async def _serve_pool(self, pool_name: str) -> None:
        ioctx = self.mgr.client.open_ioctx(pool_name)
        schedules = await self.schedule_ls(ioctx)
        if not schedules:
            return
        from ceph_tpu.rbd import RBD

        rbd = RBD()
        now = time.time()
        for key, sched in schedules.items():
            last = self._last_run.get((pool_name, key), 0.0)
            if now - last < float(sched.get("interval", 3600)):
                continue
            self._last_run[(pool_name, key)] = now
            kind, _, image = key.partition(SEP)
            try:
                if kind == "trash":
                    n = await rbd.trash_purge(ioctx)
                    if n:
                        log.info("rbd_support: purged %d trash"
                                 " entries from %s", n, pool_name)
                elif kind == "snap":
                    await self._scheduled_snapshot(
                        rbd, ioctx, image,
                        int(sched.get("keep", 3)))
            except (RadosError, ObjectNotFound):
                log.warning("rbd_support: schedule %r on %s failed",
                            key, pool_name, exc_info=True)

    async def _scheduled_snapshot(self, rbd, ioctx, image: str,
                                  keep: int) -> None:
        img = await rbd.open(ioctx, image)
        try:
            stamp = time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.gmtime())
            name = f"{self.SNAP_PREFIX}{stamp}"
            if name not in img.meta["snaps"]:
                await img.snap_create(name)
            # retention: prune the oldest scheduled snaps past `keep`
            # (never touches manually-created or protected snaps)
            mine = sorted(
                s for s in img.meta["snaps"]
                if s.startswith(self.SNAP_PREFIX)
                and not img.meta["snaps"][s].get("protected"))
            for stale in mine[:-keep] if keep > 0 else mine:
                await img.snap_remove(stale)
        finally:
            await img.close()

"""pg_autoscaler module: recommended pg_num per pool.

Reference parity: /root/reference/src/pybind/mgr/pg_autoscaler/module.py —
target PGs per OSD (mon_target_pg_per_osd, default 100) scaled by the
pool's replication factor, rounded to a power of two, recommendations
surfaced and (in the reference's `on` mode) applied.

Modes (the reference's pg_autoscale_mode pool knob, global here):
`warn` (default) surfaces recommendation rows and POOL_TOO_FEW_PGS-style
warnings; `on` APPLIES growth via `osd pool set pg_num` — the OSDs
split live PGs (daemon._split_pool_pgs).  Shrink recommendations are
never applied (PG merge unsupported).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ceph_tpu.mgr import MgrModule

TARGET_PG_PER_OSD = 100  # mon_target_pg_per_osd default


def _nearest_power_of_two(n: float) -> int:
    if n <= 1:
        return 1
    lo = 1 << (int(n).bit_length() - 1)
    hi = lo << 1
    # reference rounds down unless > 1.5x away from the lower power
    return hi if n >= lo * 1.5 else lo


class PgAutoscalerModule(MgrModule):
    NAME = "pg_autoscaler"

    def __init__(self, mgr, target_pg_per_osd: int = TARGET_PG_PER_OSD):
        super().__init__(mgr)
        self.target_pg_per_osd = int(
            mgr.config.get("mon_target_pg_per_osd", target_pg_per_osd))
        self.mode = str(mgr.config.get("pg_autoscale_mode", "warn"))
        self.recommendations: Dict[int, Dict[str, Any]] = {}
        self.applied: Dict[str, int] = {}

    async def serve_once(self) -> None:
        self.recommendations = self.compute()
        if self.mode != "on":
            return
        for row in self.recommendations.values():
            if not row["would_adjust"]:
                continue
            ideal = row["pg_num_ideal"]
            current = row["pg_num_current"]
            if ideal <= current:
                continue  # merge unsupported; warn-only downward
            # ratchet gradually (the reference bounds pg_num steps):
            # one 4x growth per tick keeps the split/peering storm and
            # the data movement bounded; later ticks converge the rest
            step = min(ideal, current * 4)
            rc, out = await self.mgr.client.mon_command(
                {"prefix": "osd pool set", "name": row["pool_name"],
                 "var": "pg_num", "val": step})
            if rc == 0:
                self.applied[row["pool_name"]] = step

    def compute(self) -> Dict[int, Dict[str, Any]]:
        """Per-pool rows mirroring `osd pool autoscale-status`."""
        osdmap = self.mgr.osdmap
        out: Dict[int, Dict[str, Any]] = {}
        if osdmap is None or not osdmap.pools:
            return out
        num_osds = sum(1 for o in range(osdmap.max_osd)
                       if osdmap.exists(o) and osdmap.is_in(o))
        if num_osds == 0:
            return out
        # equal-share capacity split across pools (no per-pool
        # target_size_ratio surface yet: every pool gets 1/N)
        budget = self.target_pg_per_osd * num_osds
        share = budget / len(osdmap.pools)
        for pool in osdmap.pools.values():
            # replica count multiplies PG cost on the OSDs; pool.size
            # is already the full width for both types (replica count
            # for replicated, k+m for erasure)
            width = pool.size
            ideal = _nearest_power_of_two(max(1.0, share / width))
            row = {
                "pool_name": pool.name,
                "pg_num_current": pool.pg_num,
                "pg_num_ideal": ideal,
                "replica_width": width,
                "would_adjust": _would_adjust(pool.pg_num, ideal),
            }
            out[pool.id] = row
        return out

    def health_warnings(self) -> List[str]:
        """POOL_TOO_FEW_PGS / POOL_TOO_MANY_PGS summaries."""
        out = []
        for row in (self.recommendations or self.compute()).values():
            if not row["would_adjust"]:
                continue
            kind = ("too few" if row["pg_num_ideal"] >
                    row["pg_num_current"] else "too many")
            out.append(
                f"pool {row['pool_name']} has {kind} PGs "
                f"({row['pg_num_current']}, ideal {row['pg_num_ideal']})")
        return out


def _would_adjust(current: int, ideal: int) -> bool:
    # the reference only flags when off by >= 4x (threshold 3.0 in
    # newer builds): small drift is not worth a data movement storm
    if ideal > current:
        return ideal / max(current, 1) >= 4
    return current / max(ideal, 1) >= 4

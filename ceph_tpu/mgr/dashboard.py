"""dashboard module: read-only HTTP status UI.

Reference parity: /root/reference/src/pybind/mgr/dashboard/ — the
mgr-hosted web UI over cluster state.  The reference is a full
cherrypy+angular application with auth, CRUD and a REST layer; this
build deliberately keeps the mgr surface READ-ONLY (mutations go
through the CLI/mon command path like everything else) and serves:

  GET /              one self-contained HTML status page (no assets)
  GET /api/status    cluster summary (epoch, osd counts, pools, health)
  GET /api/health    health checks
  GET /api/osds      per-OSD up/in + pg count + op counters
  GET /api/pools     pool table incl. autoscaler recommendations
  GET /api/mons      quorum state
  GET /api/df        cluster + per-pool usage (`ceph df` role)
  GET /api/log       recent cluster log lines

The HTML is rendered client-side from /api/status+osds+log by a few
lines of inline JS, auto-refreshing — same information architecture as
the reference's landing page (health tile, capacity tile, daemon
table), none of the framework weight.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional

from ceph_tpu.mgr import MgrModule

log = logging.getLogger("mgr")

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ceph_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2em;background:#fafafa}
 h1{font-size:1.3em} h2{font-size:1.05em;margin-top:1.4em}
 table{border-collapse:collapse;background:#fff}
 td,th{border:1px solid #ddd;padding:.3em .7em;font-size:.9em}
 th{background:#f0f0f0;text-align:left}
 .ok{color:#2a7} .warn{color:#b60} .err{color:#c22}
 #health{font-weight:bold}
 pre{background:#fff;border:1px solid #ddd;padding:.6em;
     font-size:.8em;max-height:14em;overflow:auto}
</style></head><body>
<h1>ceph_tpu cluster <span id="health">…</span></h1>
<div id="summary"></div>
<h2>OSDs</h2><table id="osds"></table>
<h2>Pools</h2><table id="pools"></table>
<h2>Monitors</h2><div id="mons"></div>
<h2>Cluster log</h2><pre id="log"></pre>
<script>
async function j(p){return (await fetch(p)).json()}
function row(cells,tag){return "<tr>"+cells.map(
  c=>"<"+tag+">"+c+"</"+tag+">").join("")+"</tr>"}
async function refresh(){
 try{
  const s=await j("/api/status"), o=await j("/api/osds"),
        m=await j("/api/mons"), lg=await j("/api/log");
  const st=s.health.status;
  const cls=st==="HEALTH_OK"?"ok":(st==="HEALTH_WARN"?"warn":"err");
  document.getElementById("health").innerHTML=
    "<span class='"+cls+"'>"+st+"</span>";
  let checks="";
  for(const [k,v] of Object.entries(s.health.checks||{}))
    checks+=" &mdash; "+k+": "+v.summary;
  document.getElementById("summary").innerHTML=
    "epoch "+s.epoch+" &middot; "+s.num_up_osds+"/"+s.num_osds+
    " osds up &middot; "+Object.keys(s.pools).length+" pools"+checks;
  let t="<tr><th>osd</th><th>up</th><th>in</th><th>pgs</th>"+
        "<th>ops</th></tr>";
  for(const r of o.osds) t+=row([r.id,r.up?"up":"<b class=err>down"+
    "</b>",r.in?"in":"out",r.pgs,r.ops??"-"],"td");
  document.getElementById("osds").innerHTML=t;
  let p="<tr><th>pool</th><th>id</th><th>type</th><th>size</th>"+
        "<th>pg_num</th><th>recommended</th></tr>";
  for(const r of s.pool_table) p+=row([r.name,r.id,r.type,r.size,
    r.pg_num,r.pg_num_ideal??"-"],"td");
  document.getElementById("pools").innerHTML=p;
  document.getElementById("mons").textContent=
    "quorum "+JSON.stringify(m.quorum)+" leader mon."+m.leader+
    " epoch "+m.election_epoch;
  document.getElementById("log").textContent=
    (lg.lines||[]).join("\\n");
 }catch(e){document.getElementById("health").textContent=
   "unreachable: "+e}
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


class DashboardModule(MgrModule):
    NAME = "dashboard"

    def __init__(self, mgr, port: int = 0):
        super().__init__(mgr)
        self.port = int(mgr.config.get("dashboard_port", port))
        self.addr: Optional[str] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", self.port)
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"
        log.info("mgr: dashboard on http://%s/", self.addr)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 5.0)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 \
                else None
            ctype = "application/json"
            if path in ("/", "/index.html"):
                body, status, ctype = _PAGE, "200 OK", "text/html"
            elif path and path.startswith("/api/"):
                doc = await self._api(path[len("/api/"):])
                if doc is None:
                    body, status = '{"error": "not found"}\n', \
                        "404 Not Found"
                else:
                    body, status = json.dumps(doc) + "\n", "200 OK"
            else:
                body, status = '{"error": "not found"}\n', \
                    "404 Not Found"
            payload = body.encode()
            writer.write(
                f"HTTP/1.0 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _api(self, what: str) -> Optional[Dict[str, Any]]:
        try:
            if what == "status":
                return await self._status()
            if what == "health":
                rc, health = await self.mgr.client.mon_command(
                    {"prefix": "health"})
                return health if rc == 0 else {"status": "UNKNOWN"}
            if what == "osds":
                return await self._osds()
            if what == "pools":
                doc = await self._status()
                return {"pools": doc["pool_table"]}
            if what == "mons":
                rc, stat = await self.mgr.client.mon_command(
                    {"prefix": "mon stat"})
                return stat if rc == 0 else {}
            if what == "df":
                # cluster + per-pool usage (the `ceph df` panel)
                return await self.mgr.client.df()
            if what == "log":
                rc, out = await self.mgr.client.mon_command(
                    {"prefix": "log last", "num": 50})
                if rc != 0:
                    return {"lines": []}
                return {"lines": [
                    f"[{e.get('level', 'INF')}] {e.get('who', '?')}:"
                    f" {e.get('message', '')}"
                    for e in out.get("entries", [])]}
        except Exception as e:  # surface, don't 500 silently
            return {"error": repr(e)}
        return None

    async def _status(self) -> Dict[str, Any]:
        rc, doc = await self.mgr.client.mon_command(
            {"prefix": "status"})
        if rc != 0:
            return {"error": rc}
        # pool table + autoscaler recommendations, dashboard-shaped
        recommend: Dict[str, Any] = {}
        scaler = self.mgr.modules.get("pg_autoscaler")
        if scaler is not None:
            try:
                recommend = {row["pool_name"]: row["pg_num_ideal"]
                             for row in scaler.compute().values()}
            except Exception:
                pass
        table = []
        for name, p in sorted(doc.get("pools", {}).items()):
            table.append(dict(p, name=name,
                              pg_num_ideal=recommend.get(name)))
        doc["pool_table"] = table
        return doc

    async def _osds(self) -> Dict[str, Any]:
        osdmap = self.mgr.osdmap
        if osdmap is None:
            return {"osds": []}
        pgs = self.mgr.pgs_per_osd()
        perf = await self.mgr.scrape_osd_perf()
        out = []
        for o in range(osdmap.max_osd):
            if not osdmap.exists(o):
                continue
            counters = perf.get(o, {})
            out.append({
                "id": o,
                "up": osdmap.is_up(o),
                "in": osdmap.is_in(o),
                "pgs": pgs.get(o, 0),
                "ops": counters.get("op", counters.get("ops")),
            })
        return {"osds": out}

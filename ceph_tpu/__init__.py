"""ceph_tpu — a TPU-native storage-data-path framework.

A ground-up re-architecture of Ceph's capability surface (reference:
wannabe1991/ceph, Ceph Pacific) with the compute-heavy data-path math executed
as batched tensor kernels on TPU via JAX/XLA/Pallas:

- Erasure coding: Reed-Solomon / Cauchy GF(2^8) codes as GF(2) bit-matrix
  matmuls on the MXU (reference seam: src/erasure-code/ErasureCodeInterface.h).
- CRUSH placement: rjenkins hash + straw2 selection as vmapped int32 kernels
  (reference seam: src/crush/mapper.c crush_do_rule).
- Checksums: batched crc32c / xxhash (reference seam: src/common/Checksummer.h).
- Compression candidate scoring on TPU behind a Compressor plugin registry
  (reference seam: src/compressor/Compressor.h).

The control plane (object store, placement maps, RADOS-lite daemons) is host
Python/C++ — orchestration stays off the accelerator, math goes on it.
"""

__version__ = "16.0.0-tpu.1"

# Release codename mirrors the reference's src/ceph_release scheme.
CEPH_RELEASE_NAME = "pacific-tpu"

"""Device-mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def _pow2_divisor(n: int, cap: int) -> int:
    d = 1
    while d * 2 <= cap and n % (d * 2) == 0:
        d *= 2
    return d


def make_mesh(devices: Optional[Sequence] = None,
              dp: Optional[int] = None,
              sp: Optional[int] = None) -> Mesh:
    """A ("dp", "sp") mesh over the given (default: all) devices.

    By default the sequence-parallel axis takes the largest power-of-two
    divisor of the device count up to 4 — wide enough to exercise ICI
    collectives, while most parallelism stays data-parallel (stripes are
    plentiful; a single stripe's byte axis rarely needs >4 chips).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if sp is None:
        sp = n // dp if dp else _pow2_divisor(n, 4)
    if dp is None:
        dp = n // sp
    if dp * sp != n:
        raise ValueError(f"dp({dp}) * sp({sp}) != device count ({n})")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))

"""Process-default mesh backend: the cluster's own EC device dispatch.

Every codec's device path (ec/dispatch.gf_matmul) routes here, so the
code the OSD daemon runs on a write IS the sharded pipeline that
`__graft_entry__.dryrun_multichip` compiles over N virtual devices —
a single real chip is simply the (dp=1, sp=1) mesh, multi-chip needs
no separate implementation (the SURVEY §5.7/§5.8 stance: striping
across chips is the same program over a bigger mesh).

The mesh is derived from the LIVE HEALTHY device set: chips held out
by their per-device breaker (common/circuit.py ``device:<id>``
families) are excluded, and the mesh — with its compiled pipelines —
is rebuilt whenever that set changes, so one sick chip shrinks the
mesh instead of poisoning every dispatch.  Awkward survivor counts
(3, 5, 7 chips) and chunk widths the byte axis cannot divide reshape
to a pure data-parallel (n, 1) mesh rather than raising or declining
(the partial-mesh fallback).

Matmuls are dp-sharded over the stripe batch; at sp==1 the per-device
kernel is the packed-word Pallas path (ops/gf_pallas.py) for host
inputs, the XLA bit-decomposition otherwise; at sp>1 the byte axis is
sequence-parallel and the XLA path runs with the crc combines riding
ICI collectives (parallel/striped.py).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional

import numpy as np
from ceph_tpu.common import flags

# observability: how many device dispatches the pipeline served (and
# how many stripe rows rode them — calls vs rows is the batching fill
# the encode service buys; mesh_rebuilds counts healthy-set changes) —
# the dryrun and tests assert the cluster datapath actually lands here
stats: Dict[str, int] = {"matmul_calls": 0, "batch_rows": 0,
                         "mesh_rebuilds": 0}


def healthy_devices() -> List:
    """The live device set minus chips whose per-device breaker holds
    them out and minus RETIRED HOSTS' chips (circuit.device_degraded
    consults the chip's ``host:<id>`` breaker too, so losing a host
    drops all its chips in ONE rebuild).  In a real multi-process
    group the decode-path mesh stays within this process's
    addressable devices — per-OSD decode work is host-local; the
    cross-host product path is the mesh ExecPlans in ec/plan.py.
    Never empty while jax has devices: with every chip degraded,
    device 0 is kept so the family breaker (which owns the 'device
    tier entirely down' verdict) still decides host fallback.
    CEPH_TPU_MESH=0 pins the set to one device (the single-chip kill
    switch — bit-identical to the pre-mesh behavior)."""
    import jax

    from ceph_tpu.common import circuit
    from ceph_tpu.parallel import multihost

    if multihost.is_multiprocess():
        devs = list(jax.local_devices())
    else:
        devs = list(jax.devices())
    if not flags.enabled("CEPH_TPU_MESH"):
        return devs[:1]
    healthy = [d for d in devs if not circuit.device_degraded(d.id)]
    return healthy or devs[:1]


def mesh_device_ids() -> tuple:
    """Device ids the next dispatch would ride (the `devices=`
    attribution set for device_call); () when jax is unavailable."""
    try:
        return tuple(d.id for d in healthy_devices())
    except Exception:
        return ()


_mesh_cache: Dict[tuple, object] = {}


def default_mesh():
    """The healthy-set mesh, rebuilt when the set changes (tests and
    the multichip dryrun override this symbol to pin a mesh).  A set
    spanning multiple host failure domains lays out as the hybrid
    ("dcn", "dp") stripe mesh — sp never crosses DCN."""
    from ceph_tpu.parallel import multihost
    from ceph_tpu.parallel.mesh import make_mesh

    devs = healthy_devices()
    # the same chip ids under a different cluster shape (1x8 vs 2x4
    # host domains) must NOT replay a cached mesh — spans_hosts (and
    # with it the flat-vs-hybrid layout) is a function of topology,
    # not of the id set alone
    sig = (tuple(d.id for d in devs), multihost.topology_signature())
    mesh = _mesh_cache.get(sig)
    if mesh is None:
        if _mesh_cache:
            stats["mesh_rebuilds"] += 1
        if len(_mesh_cache) > 16:       # bound churn bookkeeping
            _mesh_cache.clear()
        spans_hosts = len({multihost.host_of_id(d.id)
                           for d in devs}) > 1
        mesh = _mesh_cache[sig] = (
            multihost.hybrid_stripe_mesh(devs) if spans_hosts
            else make_mesh(devs))
    return mesh


def _mesh_for_chunk(chunk: int):
    """The dispatch mesh for a given chunk width: the healthy-set
    default, reshaped to pure data-parallel when the byte axis's sp
    split does not divide the chunk (a partial mesh reshapes, it
    never raises)."""
    from ceph_tpu.parallel import multihost
    from ceph_tpu.parallel.mesh import make_mesh

    mesh = default_mesh()
    sp = dict(mesh.shape).get("sp", 1)
    if sp > 1 and chunk % sp:
        devs = list(mesh.devices.flat)
        key = (tuple(d.id for d in devs), "dp-only",
               multihost.topology_signature())
        flat = _mesh_cache.get(key)
        if flat is None:
            flat = _mesh_cache[key] = make_mesh(devs, dp=len(devs),
                                                sp=1)
        mesh = flat
    return mesh


def _mesh_sig(mesh) -> tuple:
    """Process-local identity of a mesh: device ids + axis shape (a
    pipeline compiled for a dead chip's mesh must not serve the
    shrunken survivor set)."""
    return (tuple(d.id for d in mesh.devices.flat),
            tuple(dict(mesh.shape).items()))


@functools.lru_cache(maxsize=64)
def _pipeline(k: int, r: int, chunk: int, mesh_sig: tuple = ()):
    """Keyed by SHAPE + mesh signature: matrices ride as runtime
    operands (decode cycles through per-erasure-signature matrices —
    keying on the matrix would rebuild and recompile per signature);
    the mesh signature retires pipelines when the healthy set
    changes."""
    from ceph_tpu.models import reed_solomon as rs
    from ceph_tpu.parallel.striped import ShardedPipeline

    return ShardedPipeline(_mesh_for_chunk(chunk), k, r, chunk,
                           rs.reed_sol_van_matrix(k, r))


def matmul(mat: np.ndarray, data) -> Optional[np.ndarray]:
    """(R,K) GF(2^8) matrix x (K,S)/(B,K,S) uint8 over the healthy
    mesh; None when the input cannot ride the mesh (caller falls back
    to the single-device path)."""
    if not isinstance(data, np.ndarray):
        return None
    arr = data
    squeeze = False
    if arr.ndim == 2:
        arr = arr[None]
        squeeze = True
    b, k, s = arr.shape
    if s == 0 or s % 4:
        return None
    from ceph_tpu.parallel.striped import data_parallel_size

    mesh = _mesh_for_chunk(s)
    dp = data_parallel_size(mesh)
    pipe = _pipeline(k, len(mat), s, _mesh_sig(mesh))
    pad = -b % dp
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad, k, s), dtype=np.uint8)], axis=0)
    stats["matmul_calls"] += 1
    stats["batch_rows"] += b
    out = np.asarray(pipe.matmul(np.asarray(mat, np.uint8), arr))
    if pad:
        out = out[:b]
    return out[0] if squeeze else out

"""Process-default mesh backend: the cluster's own EC device dispatch.

Every codec's device path (ec/dispatch.gf_matmul) routes here, so the
code the OSD daemon runs on a write IS the sharded pipeline that
`__graft_entry__.dryrun_multichip` compiles over N virtual devices —
a single real chip is simply the (dp=1, sp=1) mesh, multi-chip needs
no separate implementation (the SURVEY §5.7/§5.8 stance: striping
across chips is the same program over a bigger mesh).

Matmuls are dp-sharded over the stripe batch; at sp==1 the per-device
kernel is the packed-word Pallas path (ops/gf_pallas.py) for host
inputs, the XLA bit-decomposition otherwise; at sp>1 the byte axis is
sequence-parallel and the XLA path runs with the crc combines riding
ICI collectives (parallel/striped.py).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import numpy as np

# observability: how many device dispatches the pipeline served (and
# how many stripe rows rode them — calls vs rows is the batching fill
# the encode service buys) — the dryrun and tests assert the cluster
# datapath actually lands here
stats: Dict[str, int] = {"matmul_calls": 0, "batch_rows": 0}


@functools.lru_cache(maxsize=1)
def default_mesh():
    import jax

    from ceph_tpu.parallel.mesh import make_mesh

    return make_mesh(jax.devices())


@functools.lru_cache(maxsize=64)
def _pipeline(k: int, r: int, chunk: int):
    """Keyed by SHAPE only: matrices ride as runtime operands (decode
    cycles through per-erasure-signature matrices — keying on the
    matrix would rebuild and recompile per signature)."""
    from ceph_tpu.models import reed_solomon as rs
    from ceph_tpu.parallel.striped import ShardedPipeline

    return ShardedPipeline(default_mesh(), k, r, chunk,
                           rs.reed_sol_van_matrix(k, r))


def matmul(mat: np.ndarray, data) -> Optional[np.ndarray]:
    """(R,K) GF(2^8) matrix x (K,S)/(B,K,S) uint8 over the default
    mesh; None when the input cannot ride the mesh (caller falls back
    to the single-device path)."""
    if not isinstance(data, np.ndarray):
        return None
    mesh = default_mesh()
    sp = mesh.shape["sp"]
    dp = mesh.shape["dp"]
    arr = data
    squeeze = False
    if arr.ndim == 2:
        arr = arr[None]
        squeeze = True
    b, k, s = arr.shape
    if s == 0 or s % sp or s % 4:
        return None
    pipe = _pipeline(k, len(mat), s)
    pad = -b % dp
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad, k, s), dtype=np.uint8)], axis=0)
    stats["matmul_calls"] += 1
    stats["batch_rows"] += b
    out = np.asarray(pipe.matmul(np.asarray(mat, np.uint8), arr))
    if pad:
        out = out[:b]
    return out[0] if squeeze else out

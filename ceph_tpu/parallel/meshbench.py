"""Multi-chip mesh bench + probe: the scale-out proof as one module.

Two entry points, shared by bench.py (the `_mesh_probe` pre-contract
check and the budget-gated `bench_mesh` sweep section), the multichip
driver tail (`__graft_entry__.dryrun_multichip`), and the test tier:

* ``probe_report()`` — correctness: the SAME stripe batch through the
  single-device plan, the N-device mesh plan, and the host numpy
  oracle must be bit-identical; then a scripted sick chip
  (``CEPH_TPU_INJECT_DEVICE_FAIL=sick=<id>``) must shrink the mesh —
  breaker tripped, survivors re-planned, output still bit-exact,
  ZERO host fallbacks.
* ``sweep_report(sizes)`` — throughput: the same fused encode+crc
  workload at mesh sizes 1 -> 2 -> 4 -> 8 (capped at the visible
  device count via CEPH_TPU_MESH_MAX_DEVICES), GiB/s of data bytes
  per size and the speedup over the single-chip leg.  On real
  multi-chip hardware near-linear scaling is the acceptance shape;
  on a single-core host with virtual devices the sweep still proves
  the plans compile and stay bit-exact at every size.
* ``multihost_report(processes)`` — the CROSS-HOST legs (PR-13
  tentpole proof): a ``--processes`` sweep axis spawning real
  ``jax.distributed`` process groups (each worker bootstraps through
  the ``parallel/multihost.py`` seam, devices split per process,
  hybrid DCN x ICI mesh) with bit-exactness vs the single-process
  leg and the host oracle; plus a HOST-LOSS shrink leg over the
  emulated 2-host topology — ``down_host=<H>`` injection must retire
  the host as ONE event (host:<id> breaker, no per-chip storm),
  re-plan on the survivor host in one shrink, zero host(CPU)
  fallbacks, ``fused-crc`` family still closed, output bit-exact.

CLI (``python -m ceph_tpu.parallel.meshbench
--probe|--sweep|--processes 1,2``) prints ONE JSON line — bench.py
runs it as a subprocess so the device-count virtualization
(XLA_FLAGS) can be applied before the backend initializes, and a
wedged tunnel stays contained.  ``--worker`` is the internal
per-process entry the ``--processes`` driver spawns.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np

from ceph_tpu.common import flags

_SWEEP_SIZES = (1, 2, 4, 8)


@contextlib.contextmanager
def _mesh_gates_open():
    """Hold the mesh byte gate open for the measurement, RESTORING it
    after: the dryrun driver tail runs these reports in-process, and
    a leaked CEPH_TPU_MESH_MIN_BYTES=0 would make every later tiny
    batch in that process mesh (the 1 MiB floor silently gone)."""
    prev = flags.peek("CEPH_TPU_MESH_MIN_BYTES")
    flags.setdefault("CEPH_TPU_MESH_MIN_BYTES", "0")
    try:
        yield
    finally:
        if prev is None:
            flags.clear("CEPH_TPU_MESH_MIN_BYTES")
        else:
            flags.set_flag("CEPH_TPU_MESH_MIN_BYTES", prev)


def ensure_devices(n: int = 8) -> int:
    """Make >= n devices visible when the platform allows it: real
    accelerator devices are used as-is; the CPU backend is virtualized
    via xla_force_host_platform_device_count (must run before the
    backend initializes — the reason bench.py subprocesses this
    module).  Returns the visible device count."""
    import re

    xla_flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  xla_flags)
    if m is None:
        xla_flags += f" --xla_force_host_platform_device_count={n}"
    elif int(m.group(1)) < n:
        xla_flags = (xla_flags[:m.start()] +
                     f"--xla_force_host_platform_device_count={n}" +
                     xla_flags[m.end():])
    os.environ["XLA_FLAGS"] = xla_flags.strip()

    import jax

    return len(jax.devices())


def _workload(smoke: bool):
    from ceph_tpu.models import reed_solomon as rs

    if smoke:
        k, m, chunk, batch = 4, 2, 16 * 1024, 32
    else:
        k, m, chunk, batch = 8, 3, 256 * 1024, 64
    rng = np.random.default_rng(929)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    return rs.reed_sol_van_matrix(k, m), data, m


def _host_oracle(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    from ceph_tpu.ops import gf

    return np.stack([gf.gf_matmul_host(matrix, data[i])
                     for i in range(data.shape[0])])


def _encode_crc(matrix, data, max_devices: int):
    """One fused encode+crc through the plan cache with the mesh
    capped at `max_devices` chips (0 = single-device plans only)."""
    from ceph_tpu.ec import plan

    prev = flags.peek("CEPH_TPU_MESH_MAX_DEVICES")
    prev_mesh = flags.peek("CEPH_TPU_MESH")
    try:
        if max_devices <= 1:
            flags.set_flag("CEPH_TPU_MESH", "0")
        else:
            flags.set_flag("CEPH_TPU_MESH", "1")
            flags.set_flag("CEPH_TPU_MESH_MAX_DEVICES",
                           str(max_devices))
        return plan.encode_with_crc(matrix, data, sig="meshbench")
    finally:
        for name, val in (("CEPH_TPU_MESH_MAX_DEVICES", prev),
                          ("CEPH_TPU_MESH", prev_mesh)):
            if val is None:
                flags.clear(name)
            else:
                flags.set_flag(name, val)


def probe_report(smoke: bool = True) -> dict:
    """The pre-contract mesh probe: bit-exactness across 1-device /
    N-device / host oracle, then the sick-chip shrink leg.  Raises on
    any violated invariant (the caller reports the probe failed)."""
    with _mesh_gates_open():
        return _probe_report(smoke)


def _probe_report(smoke: bool) -> dict:
    from ceph_tpu.common import circuit
    from ceph_tpu.ec import plan

    n = ensure_devices()
    matrix, data, m = _workload(smoke)
    oracle = _host_oracle(matrix, data)
    circuit.reset_all()
    plan.reset_stats()

    single = _encode_crc(matrix, data, 1)
    meshed = _encode_crc(matrix, data, n)
    bitexact = int(
        single is not None and meshed is not None
        and np.array_equal(single[0], oracle)
        and np.array_equal(meshed[0], oracle)
        and np.array_equal(single[1], meshed[1]))
    mesh_dispatches = plan.stats()["mesh_dispatches"]

    # sick-chip leg: the LAST device starts failing; the dispatch
    # must shrink the mesh (probe -> trip -> re-plan) and stay
    # bit-exact with ZERO host fallbacks.  Not applicable on a
    # single-device environment (no mesh to shrink).
    if n < 2:
        return {
            "devices": n,
            "bitexact": bitexact,
            "mesh_dispatches": mesh_dispatches,
            "sick_chip_shrunk": None,
            "host_fallbacks": plan.stats()["host_fallbacks"],
        }
    sick_chip_shrunk = 0
    host_fallbacks = -1
    prev_inject = flags.peek("CEPH_TPU_INJECT_DEVICE_FAIL")
    try:
        import jax

        sick_id = jax.devices()[-1].id
        flags.set_flag("CEPH_TPU_INJECT_DEVICE_FAIL",
                       f"sick={sick_id}")
        out = _encode_crc(matrix, data, n)
        st = plan.stats()
        host_fallbacks = st["host_fallbacks"]
        # NOTE: no healthy-list assertion — the device breaker's
        # full-jitter backoff is uniform from zero, so the chip may
        # legitimately read re-admittable within milliseconds (its
        # next dispatch is the half-open probe).  The invariants are:
        # the dispatch SUCCEEDED bit-exactly, a shrink happened, the
        # chip's breaker tripped, and nothing fell to host.
        sick_chip_shrunk = int(
            out is not None
            and np.array_equal(out[0], oracle)
            and st["mesh_shrinks"] >= 1
            and host_fallbacks == 0
            and circuit.device_breaker(sick_id).state == "open")
    finally:
        if prev_inject is None:
            flags.clear("CEPH_TPU_INJECT_DEVICE_FAIL")
        else:
            flags.set_flag("CEPH_TPU_INJECT_DEVICE_FAIL",
                           prev_inject)
        circuit.reset_all()
    return {
        "devices": n,
        "bitexact": bitexact,
        "mesh_dispatches": mesh_dispatches,
        "sick_chip_shrunk": sick_chip_shrunk,
        "host_fallbacks": host_fallbacks,
    }


def sweep_report(sizes: Optional[List[int]] = None,
                 smoke: bool = True, iters: int = 3) -> dict:
    """GiB/s of data bytes per mesh size, best-of-`iters` after a
    compile/warm pass, bit-exactness asserted at every size against
    the single-chip leg's parity."""
    with _mesh_gates_open():
        return _sweep_report(sizes, smoke, iters)


def _sweep_report(sizes: Optional[List[int]], smoke: bool,
                  iters: int) -> dict:
    n = ensure_devices()
    matrix, data, m = _workload(smoke)
    nbytes = data.nbytes
    sizes = [s for s in (sizes or _SWEEP_SIZES) if s <= n]
    rows = []
    base_out = None
    base_gibs = None
    for size in sizes:
        out = _encode_crc(matrix, data, size)  # compile + warm
        if out is None:
            rows.append({"devices": size, "gibs": None,
                         "speedup_x": None})
            continue
        if base_out is None:
            base_out = out
        else:
            assert np.array_equal(out[0], base_out[0]), \
                f"mesh size {size} parity != single-chip parity"
            assert np.array_equal(out[1], base_out[1]), \
                f"mesh size {size} crcs != single-chip crcs"
        best = float("inf")
        for _ in range(max(iters, 1)):
            t0 = time.perf_counter()
            _encode_crc(matrix, data, size)
            best = min(best, time.perf_counter() - t0)
        gibs = nbytes / best / (1 << 30)
        if base_gibs is None:
            base_gibs = gibs
        rows.append({"devices": size, "gibs": round(gibs, 3),
                     "speedup_x": round(gibs / base_gibs, 2)
                     if base_gibs else None})
    speedups = [r["speedup_x"] for r in rows
                if r["speedup_x"] is not None]
    return {
        "mesh_sweep": rows,
        "mesh_devices_visible": n,
        "mesh_speedup_max_x": max(speedups) if speedups else None,
        "mesh_workload_bytes": nbytes,
        "mesh_smoke": bool(smoke),
    }


# ---------------------------------------------------------------------------
# Multi-host legs: real process groups + the emulated host-loss shrink
# ---------------------------------------------------------------------------


def host_loss_report(smoke: bool = True) -> dict:
    """The host-loss shrink leg, hermetic in one process: the
    EMULATED 2-host topology (CEPH_TPU_MULTIHOST_HOSTS=2 over the
    virtual devices) with ``down_host=1`` injection.  Losing the host
    must be ONE event — its ``host:<id>`` breaker trips once, every
    chip reads degraded through it with ZERO per-chip breaker trips —
    the dispatch re-plans on the survivor host in ONE shrink, nothing
    falls back to the host CPU path, the ``fused-crc`` family stays
    closed, and the output is bit-exact."""
    from ceph_tpu.common import circuit
    from ceph_tpu.ec import plan
    from ceph_tpu.parallel import multihost

    n = ensure_devices()
    if n < 2:
        return {"multihost_hosts": 1, "host_loss_shrunk": None}
    saved = {k: flags.peek(k) for k in
             ("CEPH_TPU_MULTIHOST_HOSTS",
              "CEPH_TPU_INJECT_DEVICE_FAIL")}
    flags.set_flag("CEPH_TPU_MULTIHOST_HOSTS", "2")
    matrix, data, m = _workload(smoke)
    oracle = _host_oracle(matrix, data)
    try:
        with _mesh_gates_open():
            circuit.reset_all()
            plan.reset_stats()
            clean = _encode_crc(matrix, data, n)
            flags.set_flag("CEPH_TPU_INJECT_DEVICE_FAIL",
                           "down_host=1")
            lost = _encode_crc(matrix, data, n)
            st = plan.stats()
            chip_trips = sum(
                1 for d in range(n)
                if circuit.device_breaker(d).state != circuit.CLOSED)
            return {
                "multihost_hosts": 2,
                "host_loss_bitexact": int(
                    clean is not None and lost is not None
                    and np.array_equal(clean[0], oracle)
                    and np.array_equal(lost[0], oracle)),
                "host_loss_shrunk": int(st["mesh_shrinks"] == 1),
                "host_retirements": st["host_retirements"],
                "host_loss_one_event": int(
                    st["host_retirements"] == 1 and chip_trips == 0),
                "host_loss_host_fallbacks": st["host_fallbacks"],
                "host_loss_fused_crc_closed": int(
                    circuit.breaker("fused-crc").state
                    == circuit.CLOSED),
            }
    finally:
        for k, v in saved.items():
            if v is None:
                flags.clear(k)
            else:
                flags.set_flag(k, v)
        circuit.reset_all()


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_process_group(nproc: int, smoke: bool,
                         timeout_s: float) -> Optional[dict]:
    """Spawn a real ``jax.distributed`` group of `nproc` CPU worker
    processes (2 virtual devices each) running the fused encode+crc
    workload over the hybrid DCN x ICI mesh; returns worker 0's JSON
    report or None.  `timeout_s` is ONE shared deadline for the whole
    group (not per worker), and every worker arms its own
    self-destruct at deadline+margin — if this driver is itself
    killed by an outer timeout, no grandchild stays wedged in a gloo
    collective forever."""
    import subprocess
    import sys as _sys

    port = _free_port()
    procs = []
    env_base = {k: v for k, v in os.environ.items()
                if k != "XLA_FLAGS"}
    for pid in range(nproc):
        env = dict(env_base)
        env.update({
            "CEPH_TPU_MULTIHOST_COORD": f"127.0.0.1:{port}",
            "CEPH_TPU_MULTIHOST_NPROC": str(nproc),
            "CEPH_TPU_MULTIHOST_PID": str(pid),
            "CEPH_TPU_MULTIHOST_LOCAL_DEVICES": "2",
            "CEPH_TPU_MESH_MIN_BYTES": "0",
            "JAX_PLATFORMS": "cpu",
            # orphan bound: the worker exits on its own even when
            # nothing is left alive to kill it
            "CEPH_TPU_MULTIHOST_WORKER_DEADLINE_S":
                str(timeout_s + 30.0),
        })
        if smoke:
            env["CEPH_TPU_BENCH_SMOKE"] = "1"
        procs.append(subprocess.Popen(
            [_sys.executable, "-m", "ceph_tpu.parallel.meshbench",
             "--worker"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env))
    outs = []
    deadline = time.monotonic() + timeout_s
    try:
        for p in procs:
            so, se = p.communicate(
                timeout=max(deadline - time.monotonic(), 0.1))
            outs.append((p.returncode, so, se))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print(f"# multihost {nproc}-process group timed out",
              file=sys.stderr)
        return None
    for rc, so, se in outs:
        if rc != 0:
            print(f"# multihost worker failed rc={rc}:"
                  f" {se[-800:]}", file=sys.stderr)
            return None
    reports = []
    for _rc, so, _se in outs:
        lines = [ln for ln in so.strip().splitlines() if ln]
        try:
            reports.append(json.loads(lines[-1]) if lines else None)
        except json.JSONDecodeError:
            reports.append(None)
    rep = reports[0]
    if rep is None:
        return None
    # collective cross-check (armed via CEPH_TPU_COLLECTIVE_TRACE=1,
    # inherited by the workers): every process must observe the SAME
    # collective sequence — a divergent trace is the silent-wedge
    # class rules_spmd.py flags statically
    traces = [r.get("collective_trace") if r else None
              for r in reports]
    if all(t is not None for t in traces):
        rep = dict(rep)
        rep["spmd_trace"] = traces[0]
        rep["spmd_order_congruent"] = int(
            all(t == traces[0] for t in traces[1:]))
        rep.pop("collective_trace", None)
    return rep


def worker_report(smoke: bool = True, iters: int = 3) -> dict:
    """One process's leg of the ``--processes`` sweep: bootstrap the
    group through the multihost seam, run the shared workload through
    the plan cache's mesh path (hybrid mesh, pre-sharded global
    arrays, allgathered outputs), check bit-exactness against the
    host oracle every process computes locally."""
    from ceph_tpu.ec import plan
    from ceph_tpu.parallel import multihost

    deadline = flags.get("CEPH_TPU_MULTIHOST_WORKER_DEADLINE_S")
    if deadline:
        import threading

        # self-destruct: a worker orphaned mid-collective (its driver
        # killed by an outer timeout) must not outlive the round
        t = threading.Timer(float(deadline), lambda: os._exit(124))
        t.daemon = True
        t.start()
    if not multihost.bootstrap_from_env():
        ensure_devices()        # single-process leg in the driver
    import jax

    matrix, data, m = _workload(smoke)
    oracle = _host_oracle(matrix, data)
    n = len(jax.devices())
    with _mesh_gates_open():
        out = _encode_crc(matrix, data, n)  # compile + warm
        bitexact = int(out is not None
                       and np.array_equal(out[0], oracle))
        best = float("inf")
        for _ in range(max(iters, 1)):
            t0 = time.perf_counter()
            _encode_crc(matrix, data, n)
            best = min(best, time.perf_counter() - t0)
    st = plan.stats()
    rep = {
        "processes": multihost.process_count(),
        "process_index": multihost.process_index(),
        "devices": n,
        "hosts": multihost.host_count(),
        "bitexact": bitexact,
        "gibs": round(data.nbytes / best / (1 << 30), 3),
        "mesh_dispatches": st["mesh_dispatches"],
        "topology": list(multihost.topology_signature()) or None,
    }
    from ceph_tpu.analysis import interleave

    if interleave.collective_trace_armed():
        rep["collective_trace"] = [
            [r.path, r.line, r.op]
            for r in interleave.collective_records()]
    return rep


def multihost_report(processes: Optional[List[int]] = None,
                     smoke: bool = True) -> dict:
    """The ``--processes`` sweep axis + the host-loss shrink leg —
    the bench_multihost section's body and the `multihost` contract
    key's source."""
    counts = processes or [1, 2]
    # per-leg deadline: strictly below bench.py's subprocess timeouts
    # (probe 180 / sweep 300), so THIS driver always kills and reaps
    # its worker group before the outer timeout kills the driver
    timeout_s = flags.flag_float("CEPH_TPU_MULTIHOST_LEG_TIMEOUT_S")
    rows = []
    all_bitexact = 1
    for nproc in counts:
        if nproc <= 1:
            rep = worker_report(smoke=smoke)
            rep.pop("process_index", None)
        else:
            rep = _spawn_process_group(nproc, smoke, timeout_s)
        if rep is None:
            rows.append({"processes": nproc, "bitexact": None})
            all_bitexact = 0
            continue
        rep.pop("process_index", None)
        rows.append(rep)
        if not rep.get("bitexact"):
            all_bitexact = 0
    out = {
        "process_sweep": rows,
        "multihost_bitexact": all_bitexact,
        "processes_max": max(counts),
    }
    out.update(host_loss_report(smoke=smoke))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="meshbench")
    ap.add_argument("--probe", action="store_true")
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--sizes", type=str, default="")
    ap.add_argument("--processes", type=str, default="",
                    help="multihost sweep axis: process counts, e.g."
                    " 1,2")
    ap.add_argument("--worker", action="store_true",
                    help="internal: one process of a --processes"
                    " group")
    args = ap.parse_args(argv)
    smoke = args.smoke or flags.get("CEPH_TPU_BENCH_SMOKE") == "1"
    if args.worker:
        print(json.dumps(worker_report(smoke=smoke)), flush=True)
        return 0
    out = {}
    if args.probe or not (args.sweep or args.processes):
        out.update(probe_report(smoke=smoke))
    if args.sweep:
        sizes = [int(s) for s in args.sizes.split(",") if s] or None
        out.update(sweep_report(sizes=sizes, smoke=smoke))
    if args.processes:
        counts = [int(p) for p in args.processes.split(",") if p]
        out.update(multihost_report(processes=counts, smoke=smoke))
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

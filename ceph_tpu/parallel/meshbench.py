"""Multi-chip mesh bench + probe: the scale-out proof as one module.

Two entry points, shared by bench.py (the `_mesh_probe` pre-contract
check and the budget-gated `bench_mesh` sweep section), the multichip
driver tail (`__graft_entry__.dryrun_multichip`), and the test tier:

* ``probe_report()`` — correctness: the SAME stripe batch through the
  single-device plan, the N-device mesh plan, and the host numpy
  oracle must be bit-identical; then a scripted sick chip
  (``CEPH_TPU_INJECT_DEVICE_FAIL=sick=<id>``) must shrink the mesh —
  breaker tripped, survivors re-planned, output still bit-exact,
  ZERO host fallbacks.
* ``sweep_report(sizes)`` — throughput: the same fused encode+crc
  workload at mesh sizes 1 -> 2 -> 4 -> 8 (capped at the visible
  device count via CEPH_TPU_MESH_MAX_DEVICES), GiB/s of data bytes
  per size and the speedup over the single-chip leg.  On real
  multi-chip hardware near-linear scaling is the acceptance shape;
  on a single-core host with virtual devices the sweep still proves
  the plans compile and stay bit-exact at every size.

CLI (``python -m ceph_tpu.parallel.meshbench --probe|--sweep``)
prints ONE JSON line — bench.py runs it as a subprocess so the
device-count virtualization (XLA_FLAGS) can be applied before the
backend initializes, and a wedged tunnel stays contained.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np

_SWEEP_SIZES = (1, 2, 4, 8)


@contextlib.contextmanager
def _mesh_gates_open():
    """Hold the mesh byte gate open for the measurement, RESTORING it
    after: the dryrun driver tail runs these reports in-process, and
    a leaked CEPH_TPU_MESH_MIN_BYTES=0 would make every later tiny
    batch in that process mesh (the 1 MiB floor silently gone)."""
    prev = os.environ.get("CEPH_TPU_MESH_MIN_BYTES")
    os.environ.setdefault("CEPH_TPU_MESH_MIN_BYTES", "0")
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("CEPH_TPU_MESH_MIN_BYTES", None)
        else:
            os.environ["CEPH_TPU_MESH_MIN_BYTES"] = prev


def ensure_devices(n: int = 8) -> int:
    """Make >= n devices visible when the platform allows it: real
    accelerator devices are used as-is; the CPU backend is virtualized
    via xla_force_host_platform_device_count (must run before the
    backend initializes — the reason bench.py subprocesses this
    module).  Returns the visible device count."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  flags)
    if m is None:
        flags += f" --xla_force_host_platform_device_count={n}"
    elif int(m.group(1)) < n:
        flags = (flags[:m.start()] +
                 f"--xla_force_host_platform_device_count={n}" +
                 flags[m.end():])
    os.environ["XLA_FLAGS"] = flags.strip()

    import jax

    return len(jax.devices())


def _workload(smoke: bool):
    from ceph_tpu.models import reed_solomon as rs

    if smoke:
        k, m, chunk, batch = 4, 2, 16 * 1024, 32
    else:
        k, m, chunk, batch = 8, 3, 256 * 1024, 64
    rng = np.random.default_rng(929)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    return rs.reed_sol_van_matrix(k, m), data, m


def _host_oracle(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    from ceph_tpu.ops import gf

    return np.stack([gf.gf_matmul_host(matrix, data[i])
                     for i in range(data.shape[0])])


def _encode_crc(matrix, data, max_devices: int):
    """One fused encode+crc through the plan cache with the mesh
    capped at `max_devices` chips (0 = single-device plans only)."""
    from ceph_tpu.ec import plan

    prev = os.environ.get("CEPH_TPU_MESH_MAX_DEVICES")
    prev_mesh = os.environ.get("CEPH_TPU_MESH")
    try:
        if max_devices <= 1:
            os.environ["CEPH_TPU_MESH"] = "0"
        else:
            os.environ["CEPH_TPU_MESH"] = "1"
            os.environ["CEPH_TPU_MESH_MAX_DEVICES"] = str(max_devices)
        return plan.encode_with_crc(matrix, data, sig="meshbench")
    finally:
        for name, val in (("CEPH_TPU_MESH_MAX_DEVICES", prev),
                          ("CEPH_TPU_MESH", prev_mesh)):
            if val is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = val


def probe_report(smoke: bool = True) -> dict:
    """The pre-contract mesh probe: bit-exactness across 1-device /
    N-device / host oracle, then the sick-chip shrink leg.  Raises on
    any violated invariant (the caller reports the probe failed)."""
    with _mesh_gates_open():
        return _probe_report(smoke)


def _probe_report(smoke: bool) -> dict:
    from ceph_tpu.common import circuit
    from ceph_tpu.ec import plan

    n = ensure_devices()
    matrix, data, m = _workload(smoke)
    oracle = _host_oracle(matrix, data)
    circuit.reset_all()
    plan.reset_stats()

    single = _encode_crc(matrix, data, 1)
    meshed = _encode_crc(matrix, data, n)
    bitexact = int(
        single is not None and meshed is not None
        and np.array_equal(single[0], oracle)
        and np.array_equal(meshed[0], oracle)
        and np.array_equal(single[1], meshed[1]))
    mesh_dispatches = plan.stats()["mesh_dispatches"]

    # sick-chip leg: the LAST device starts failing; the dispatch
    # must shrink the mesh (probe -> trip -> re-plan) and stay
    # bit-exact with ZERO host fallbacks.  Not applicable on a
    # single-device environment (no mesh to shrink).
    if n < 2:
        return {
            "devices": n,
            "bitexact": bitexact,
            "mesh_dispatches": mesh_dispatches,
            "sick_chip_shrunk": None,
            "host_fallbacks": plan.stats()["host_fallbacks"],
        }
    sick_chip_shrunk = 0
    host_fallbacks = -1
    prev_inject = os.environ.get("CEPH_TPU_INJECT_DEVICE_FAIL")
    try:
        import jax

        sick_id = jax.devices()[-1].id
        os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = f"sick={sick_id}"
        out = _encode_crc(matrix, data, n)
        st = plan.stats()
        host_fallbacks = st["host_fallbacks"]
        # NOTE: no healthy-list assertion — the device breaker's
        # full-jitter backoff is uniform from zero, so the chip may
        # legitimately read re-admittable within milliseconds (its
        # next dispatch is the half-open probe).  The invariants are:
        # the dispatch SUCCEEDED bit-exactly, a shrink happened, the
        # chip's breaker tripped, and nothing fell to host.
        sick_chip_shrunk = int(
            out is not None
            and np.array_equal(out[0], oracle)
            and st["mesh_shrinks"] >= 1
            and host_fallbacks == 0
            and circuit.device_breaker(sick_id).state == "open")
    finally:
        if prev_inject is None:
            os.environ.pop("CEPH_TPU_INJECT_DEVICE_FAIL", None)
        else:
            os.environ["CEPH_TPU_INJECT_DEVICE_FAIL"] = prev_inject
        circuit.reset_all()
    return {
        "devices": n,
        "bitexact": bitexact,
        "mesh_dispatches": mesh_dispatches,
        "sick_chip_shrunk": sick_chip_shrunk,
        "host_fallbacks": host_fallbacks,
    }


def sweep_report(sizes: Optional[List[int]] = None,
                 smoke: bool = True, iters: int = 3) -> dict:
    """GiB/s of data bytes per mesh size, best-of-`iters` after a
    compile/warm pass, bit-exactness asserted at every size against
    the single-chip leg's parity."""
    with _mesh_gates_open():
        return _sweep_report(sizes, smoke, iters)


def _sweep_report(sizes: Optional[List[int]], smoke: bool,
                  iters: int) -> dict:
    n = ensure_devices()
    matrix, data, m = _workload(smoke)
    nbytes = data.nbytes
    sizes = [s for s in (sizes or _SWEEP_SIZES) if s <= n]
    rows = []
    base_out = None
    base_gibs = None
    for size in sizes:
        out = _encode_crc(matrix, data, size)  # compile + warm
        if out is None:
            rows.append({"devices": size, "gibs": None,
                         "speedup_x": None})
            continue
        if base_out is None:
            base_out = out
        else:
            assert np.array_equal(out[0], base_out[0]), \
                f"mesh size {size} parity != single-chip parity"
            assert np.array_equal(out[1], base_out[1]), \
                f"mesh size {size} crcs != single-chip crcs"
        best = float("inf")
        for _ in range(max(iters, 1)):
            t0 = time.perf_counter()
            _encode_crc(matrix, data, size)
            best = min(best, time.perf_counter() - t0)
        gibs = nbytes / best / (1 << 30)
        if base_gibs is None:
            base_gibs = gibs
        rows.append({"devices": size, "gibs": round(gibs, 3),
                     "speedup_x": round(gibs / base_gibs, 2)
                     if base_gibs else None})
    speedups = [r["speedup_x"] for r in rows
                if r["speedup_x"] is not None]
    return {
        "mesh_sweep": rows,
        "mesh_devices_visible": n,
        "mesh_speedup_max_x": max(speedups) if speedups else None,
        "mesh_workload_bytes": nbytes,
        "mesh_smoke": bool(smoke),
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="meshbench")
    ap.add_argument("--probe", action="store_true")
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--sizes", type=str, default="")
    args = ap.parse_args(argv)
    smoke = args.smoke or os.environ.get(
        "CEPH_TPU_BENCH_SMOKE") == "1"
    out = {}
    if args.probe or not args.sweep:
        out.update(probe_report(smoke=smoke))
    if args.sweep:
        sizes = [int(s) for s in args.sizes.split(",") if s] or None
        out.update(sweep_report(sizes=sizes, smoke=smoke))
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Sharded storage pipeline: EC encode/decode + hinfo CRC over a chip mesh.

This is the multi-chip version of the EC-on-OSD hot path (SURVEY.md §3.2):
stripe batches are data-parallel over the mesh "dp" axis, and each chunk's
byte axis is sequence-parallel over "sp" — the striping idea of
libradosstriper/ECUtil (reference src/osd/ECUtil.h:27-80) mapped onto ICI.

Per step, entirely on-device under one shard_map:
  1. parity = GF(2^8) generator matmul (bit-decomposed on the MXU); purely
     local — the byte axis is elementwise for the code, so "sp" needs no
     collective here;
  2. per-chunk hinfo crc32c (ECUtil::HashInfo, reference ECUtil.h:101-160):
     each device folds its byte segment to 32 partial-CRC bits, then an
     all_gather over "sp" + log-free linear fold with zero-run advance
     matrices combines segments — the cross-chip traffic is 32 bits per
     chunk, not the data;
  3. optional CRUSH placement of each stripe's PG via the vmapped straw2
     kernel (replicated over "sp").

Decode runs the same matmul with host-inverted decode rows
(ErasureCodeIsa-style table cache lives in the codec).
"""

from __future__ import annotations

import functools
import inspect
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.ec import plan
from ceph_tpu.ops import checksum as cks
from ceph_tpu.ops import gf


def _shard_map(f, *, mesh, in_specs, out_specs):
    """jax.shard_map with the pre-0.6 spelling as fallback: older jax
    ships it as jax.experimental.shard_map.shard_map, and the
    replication-check knob was renamed check_rep -> check_vma
    independently of the move, so pick it off the actual signature
    (0.5.x-era releases have jax.shard_map but still say check_rep)."""
    if hasattr(jax, "shard_map"):
        params = inspect.signature(jax.shard_map).parameters
        knob = "check_vma" if "check_vma" in params else "check_rep"
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **{knob: False})
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


# ---------------------------------------------------------------------------
# Logical axis rules (the T5X partitioner pattern, SNIPPETS [1]/[2])
# ---------------------------------------------------------------------------

# The EC data plane's logical axes and where each lands on the chip
# mesh.  `stripe` is data-parallel over the DCN-across-hosts x
# ICI-within-host data axes — ("dcn", "dp"), the T5X hybrid-mesh
# pattern: stripes are plentiful and independent, so the slow
# cross-host interconnect carries nothing per-byte; `shard` (the k+m
# chunk axis) stays WITHIN a chip — a stripe's shards share the
# generator matmul, and splitting them would turn a local MXU product
# into cross-chip traffic; `byte` may be sequence-parallel over "sp"
# (elementwise for the code, so only the 32-bit CRC fold ever crosses
# ICI — and never DCN).  The product-path mesh plans (ec/plan.py) use
# stripe-parallel meshes (hybrid ("dcn", "dp") across hosts, flat
# ("dp",) within one); the dryrun exercises the sp>1 byte split.
LOGICAL_AXIS_RULES = (("stripe", ("dcn", "dp")), ("shard", None),
                      ("byte", "sp"))


def logical_spec(*logical_axes, rules=LOGICAL_AXIS_RULES,
                 mesh: Optional[Mesh] = None):
    """PartitionSpec for an array whose dims carry the given logical
    axis names (None = unnamed/replicated dim).  A rule may map to
    ONE mesh axis or a TUPLE of them (`stripe` -> ("dcn", "dp"));
    axes ABSENT from `mesh` are dropped — a single-host ("dp",)
    stripe mesh resolves `stripe` to plain "dp", a hybrid mesh to the
    ("dcn", "dp") pair, and a mesh with neither to replicated — so
    the same array spec works on any mesh shape, which is what lets a
    shrunken (or single-host) mesh reuse the same kernel builders."""
    table = dict(rules)
    names = []
    axes = set(mesh.axis_names) if mesh is not None else None
    for ax in logical_axes:
        m = table.get(ax) if ax is not None else None
        if isinstance(m, tuple):
            present = tuple(a for a in m
                            if axes is None or a in axes)
            m = (None if not present
                 else present[0] if len(present) == 1 else present)
        elif m is not None and axes is not None and m not in axes:
            m = None
        names.append(m)
    return P(*names)


def data_parallel_size(mesh: Mesh) -> int:
    """The number of stripe-parallel ways a mesh provides: the
    product of its data axes (dcn x dp) — what batch divisibility and
    per-chip whole-stripe rounding key on."""
    shape = dict(mesh.shape)
    return shape.get("dcn", 1) * shape.get("dp", 1)


def stripe_mesh(devices) -> Mesh:
    """A stripe-parallel mesh over the given devices: one stripe
    sub-batch per chip, shards and bytes within-chip — the product
    path's mesh shape (ec/plan.py mesh plans).  Devices spanning more
    than one host (parallel/multihost.py topology) lay out as a
    hybrid ("dcn", "dp") mesh — DCN across hosts, dp within — and a
    single host's set stays the flat ("dp",) mesh, bit-identical to
    the PR-9 shape."""
    from ceph_tpu.parallel import multihost

    return multihost.hybrid_stripe_mesh(devices)


def build_mesh_encode(mesh: Mesh, label: str):
    """Compiled mesh EC encode: (mbits, (B, k, S)) -> (B, m, S) with
    the stripe batch sharded over "dp".  The GF(2) bit-matmul is
    purely local per chip (the byte axis is elementwise for the
    code), so there is no collective at all — near-linear scaling is
    the expected shape.  Returns (jitted_fn, input_sharding); callers
    device_put the batch with the sharding first (the pre-sharded-
    input discipline, SNIPPETS [3]) so dispatch never re-lands bytes
    on host between stages."""
    from ceph_tpu.ec import plan

    data_spec = logical_spec("stripe", "shard", "byte", mesh=mesh)
    fn = _shard_map(gf._gf2_matmul_bytes_impl, mesh=mesh,
                    in_specs=(P(), data_spec), out_specs=data_spec)
    return (plan.tracked_jit(label, fn),
            NamedSharding(mesh, data_spec))


def build_mesh_encode_crc(mesh: Mesh, chunk_bytes: int, label: str):
    """Compiled mesh fused encode + per-chunk zero-seeded crc32c:
    (mbits, (B, k, S)) -> (parity (B, m, S), crcs (B, k+m) packed
    bits).  Traces plan.fused_encode_crc_step — the SAME kernel the
    single-device plan jits, so single-vs-mesh bit-exactness is by
    construction — sharded stripe-parallel; with whole chunks
    on-chip the CRC needs no cross-chip fold, and parity + CRC stay
    device-resident between the stages inside ONE dispatch.  Returns
    (jitted_fn, input_sharding)."""
    from ceph_tpu.ec import plan
    from ceph_tpu.ops import checksum as cks

    consts = cks.make_crc_consts(chunk_bytes)
    data_spec = logical_spec("stripe", "shard", "byte", mesh=mesh)
    crc_spec = logical_spec("stripe", "shard", mesh=mesh)
    local_step = functools.partial(plan.fused_encode_crc_step,
                                   consts=consts)
    fn = _shard_map(local_step, mesh=mesh,
                    in_specs=(P(), data_spec),
                    out_specs=(data_spec, crc_spec))
    return (plan.tracked_jit(label, fn),
            NamedSharding(mesh, data_spec))


class ShardedPipeline:
    """A compiled multi-chip encode(+hinfo crc)(+placement) step."""

    def __init__(self, mesh: Mesh, k: int, m: int, chunk_bytes: int,
                 matrix: np.ndarray, csum_init: int = 0xFFFFFFFF,
                 placement_rule=None, result_max: int = 0):
        self.mesh = mesh
        self.k, self.m = k, m
        self.chunk_bytes = chunk_bytes
        # partial meshes (a shrunken healthy set, or a pure ("dp",)
        # stripe mesh) may lack any axis: an absent axis is size 1,
        # not an error — the same pipeline code serves every shape.
        # dp is the TOTAL stripe-parallel width (dcn x dp on a hybrid
        # multi-host mesh)
        shape = dict(mesh.shape)
        self.sp = shape.get("sp", 1)
        self.dp = data_parallel_size(mesh)
        if chunk_bytes % self.sp:
            raise ValueError(
                f"chunk_bytes {chunk_bytes} not divisible by sp={self.sp}")
        self.seg = chunk_bytes // self.sp
        self.csum_init = csum_init
        self._mbits = jnp.asarray(gf.gf_matrix_to_bits(matrix))
        self._crc_consts = cks.make_crc_consts(self.seg)
        self._advance_t = cks.make_combine_advance(self.seg)
        self._seed_adv = cks.crc32c_zeros(csum_init & 0xFFFFFFFF, chunk_bytes)
        self._placement_one = (placement_rule.trace_one
                               if placement_rule is not None else None)
        if placement_rule is not None and result_max:
            if placement_rule.result_max != result_max:
                raise ValueError(
                    f"placement_rule yields {placement_rule.result_max} osds"
                    f" per input, caller expected {result_max}")
        self._encode = self._build_encode()
        self._decode_cache = {}
        self._words_cache = {}

    # -- encode + hinfo + placement ---------------------------------------

    def _fold_segments(self, gathered):
        """(P, ..., 32) per-segment partial CRC bits -> (..., 32) total."""
        total = gathered[0]
        for p in range(1, gathered.shape[0]):
            total = cks.crc32c_combine_bits(total, gathered[p],
                                            self._advance_t)
        return total

    def _build_encode(self):
        mesh = self.mesh
        has_sp = "sp" in dict(mesh.shape)

        def local_step(mbits, data, pgs):
            # data (B_l, k, S_l); pgs (B_l,)
            parity = gf.gf2_matmul_bytes(mbits, data)
            chunks = jnp.concatenate([data, parity], axis=1)
            part = cks.crc32c_partial_bits(chunks, self._crc_consts)
            if has_sp:
                # (P, B_l, k+m, 32): combine per-segment partials
                gathered = jax.lax.all_gather(part, "sp")
            else:
                # pure stripe mesh: whole chunks on-chip, no fold
                gathered = part[None]
            crc = cks.crc32c_pack_bits(self._fold_segments(gathered))
            crc = crc ^ jnp.uint32(self._seed_adv)
            if self._placement_one is not None:
                placement = jax.vmap(self._placement_one)(pgs)
            else:
                placement = jnp.zeros((pgs.shape[0], 1), dtype=jnp.int32)
            return parity, crc, placement

        data_spec = logical_spec("stripe", "shard", "byte", mesh=mesh)
        row_spec = logical_spec("stripe", mesh=mesh)
        shard = _shard_map(
            functools.partial(local_step, self._mbits),
            mesh=mesh,
            in_specs=(data_spec, row_spec),
            out_specs=(data_spec, row_spec, row_spec),
        )
        return plan.tracked_jit(
            f"striped.encode k{self.k}m{self.m} S{self.chunk_bytes}",
            shard)

    def data_sharding(self) -> NamedSharding:
        return NamedSharding(
            self.mesh, logical_spec("stripe", "shard", "byte",
                                    mesh=self.mesh))

    def pg_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh,
                             logical_spec("stripe", mesh=self.mesh))

    def put_stripes(self, data) -> jax.Array:
        """Place a (B, k, S) host batch onto the mesh with dp/sp sharding."""
        return jax.device_put(jnp.asarray(data, dtype=jnp.uint8),
                              self.data_sharding())

    def encode(self, data, pgs=None):
        """(B, k, S) stripes [+ (B,) pg ids] -> (parity, hinfo crcs, placement).

        parity (B, m, S) stays mesh-sharded; crcs (B, k+m) uint32 and
        placement (B, R) are dp-sharded, sp-replicated.

        The dispatch rides the ec-encode breaker guard (watchdog +
        injection seam); there is no host twin at this mesh layer, so
        an unrecovered failure raises — single-chip callers reach the
        mesh through ec/dispatch.gf_matmul, which owns the bit-exact
        host degradation.
        """
        from ceph_tpu.common import circuit

        b = data.shape[0]
        if b % self.dp:
            raise ValueError(f"batch {b} not divisible by dp={self.dp}")
        if pgs is None:
            pgs = jnp.zeros((b,), dtype=jnp.int32)
        status, out = circuit.device_call(
            "ec-encode", self._encode, data,
            jnp.asarray(pgs, dtype=jnp.int32), batch=int(b),
            label="striped.encode", oom_to_fail=True,
            devices=tuple(d.id for d in self.mesh.devices.flat))
        if status != "ok":
            if isinstance(out, BaseException):
                raise out
            raise RuntimeError(
                f"striped encode unavailable ({status}: ec-encode"
                " breaker)")
        return out

    # -- decode -----------------------------------------------------------

    def _decode_fn(self, rows: int):
        fn = self._decode_cache.get(rows)
        if fn is None:
            mesh = self.mesh

            def local(dmat_bits, survivors):
                return gf.gf2_matmul_bytes(dmat_bits, survivors)

            spec = logical_spec("stripe", "shard", "byte", mesh=mesh)
            shard = _shard_map(
                local, mesh=mesh,
                in_specs=(P(), spec),
                out_specs=spec,
            )
            fn = plan.tracked_jit(
                f"striped.matmul r{rows}k{self.k} S{self.chunk_bytes}",
                shard)
            self._decode_cache[rows] = fn
        return fn

    def decode(self, dmat: np.ndarray, survivors):
        """(B, k, S) surviving chunks x (R, k) decode rows -> (B, R, S)."""
        dmat_bits = jnp.asarray(gf.gf_matrix_to_bits(dmat))
        return self._decode_fn(dmat.shape[0])(dmat_bits, survivors)

    # -- generalized mesh matmul (the codec device dispatch) ---------------

    def matmul(self, mat: np.ndarray, data: np.ndarray):
        """(R, K) x (B, K, S) host batch -> (B, R, S) over the mesh.

        Encode and decode are the same product (decode rows come from
        the codec's signature cache), so this one entry serves both —
        it is what ec/dispatch routes the daemons' device path
        through.  At sp == 1 each device runs the packed-word Pallas
        kernel (host bytes view as words for free); at sp > 1 the byte
        axis is sequence-parallel and the XLA bit-decomposition runs
        under shard_map.
        """
        from ceph_tpu.ops import gf_pallas

        b, k, s = data.shape
        if self.sp == 1 and gf_pallas.supported((b, k, s)):
            return self._matmul_words(mat, data)
        dev = jax.device_put(jnp.asarray(data, dtype=jnp.uint8),
                             self.data_sharding())
        return self.decode(np.asarray(mat, dtype=np.uint8), dev)

    def _matmul_words(self, mat: np.ndarray, data: np.ndarray):
        from ceph_tpu.ops import gf_pallas

        key = gf_pallas._coeff_key(mat)
        if key in gf_pallas._registered:
            # hot encode generators: the unrolled specialized kernel,
            # one compile per registered matrix (bounded set)
            fn = self._words_cache.get(key)
            if fn is None:
                matarr = np.array(key, dtype=np.uint8)

                def local(w):
                    return gf_pallas.gf_matmul_words(matarr, w)

                fn = self._jit_words(local)
                self._words_cache[key] = fn
            args = (fn,)
        else:
            # decode matrices vary per erasure signature: ONE compile
            # per (r, k) shape, matrix as a runtime SMEM operand
            r, k = len(key), len(key[0])
            fn = self._words_cache.get((r, k))
            if fn is None:
                fn = self._jit_words(gf_pallas.gf_matmul_words_runtime,
                                     runtime_mat=True)
                self._words_cache[(r, k)] = fn
            args = (fn, jnp.asarray(
                np.asarray(mat, np.uint8).astype(np.int32)))
        words = jnp.asarray(gf_pallas.words_from_bytes(data))
        sharding = NamedSharding(
            self.mesh, logical_spec("stripe", "shard", None, None,
                                    mesh=self.mesh))
        dw = jax.device_put(words, sharding)
        out = np.asarray(args[0](*args[1:], dw))
        return gf_pallas.bytes_from_words(out)

    def _jit_words(self, local, runtime_mat: bool = False):
        spec = logical_spec("stripe", "shard", None, None,
                            mesh=self.mesh)
        in_specs = (P(), spec) if runtime_mat else (spec,)
        kind = "runtime" if runtime_mat else "spec"
        return plan.tracked_jit(
            f"striped.words.{kind} k{self.k} S{self.chunk_bytes}",
            _shard_map(local, mesh=self.mesh, in_specs=in_specs,
                       out_specs=spec))

"""Multi-process bootstrap seam: hybrid ICI/DCN meshes across hosts.

PR 9 made multi-chip the default dispatch path for batched EC work,
but its mesh stopped at one host.  This module is the cross-host
story — the T5X partitioner pattern (SNIPPETS [1]/[2]:
``multihost_utils`` + ``create_hybrid_device_mesh``-style hybrid
meshes, logical-axis rules spanning ICI-within-host /
DCN-across-hosts) applied to the EC data plane:

* **Bootstrap seam** — ``initialize()`` is the ONLY place in the tree
  allowed to call ``jax.distributed.initialize`` (the
  ``raw-process-group`` lint rule enforces it).  Multiple CPU
  processes emulate multi-host today (gloo CPU collectives; real TPU
  pods later): each worker exports ``CEPH_TPU_MULTIHOST_COORD`` /
  ``_NPROC`` / ``_PID`` and calls ``bootstrap_from_env()`` before the
  backend initializes.
* **Host topology** — every device maps to a HOST failure domain:
  its owning process in a real multi-process group, or an emulated
  block when ``CEPH_TPU_MULTIHOST_HOSTS=H`` partitions one process's
  virtual devices into H hosts (how the host-loss shrink machinery is
  exercised hermetically in tier-1).  ``topology_signature()`` is the
  process-topology element Mesh ExecPlan keys carry (process count +
  per-process device-set signature) so plans from different cluster
  shapes never collide.
* **Hybrid meshes** — ``hybrid_stripe_mesh()`` lays the device set
  out as ("dcn", "dp"): the DCN axis crosses hosts, the dp axis stays
  within a host's ICI domain.  ``parallel/striped.py``'s
  LOGICAL_AXIS_RULES map ``stripe`` across ("dcn", "dp") while
  ``shard``/``byte`` stay within-chip, so the EC kernels need no
  cross-DCN collective at all — stripes are embarrassingly parallel
  and the slow interconnect carries nothing per-byte.
* **Collective-safe membership agreement** — ``agree()`` publishes a
  per-process payload through the coordinator's key-value store and
  reads every peer's with a hard timeout: a DEAD host shows up as a
  timeout, never as a wedged collective (the reason membership cannot
  ride an allgather: the first thing a lost host breaks is exactly
  that collective).  arXiv:1804.10331's failure model is the design
  anchor: once coded work spans hosts, the unit of loss is the HOST,
  and ``parallel/backend.py`` + ``ec/plan.py`` treat it that way —
  one ``host:<id>`` breaker event retires all the host's chips
  together (no per-chip breaker storm), and plans re-key on the
  survivor processes in one shrink.

Kill switch: ``CEPH_TPU_MULTIHOST=0`` pins everything to the
single-process behavior (bit-identical to PR 9).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ceph_tpu.common import flags

__all__ = [
    "agree", "agree_healthy", "agreed_healthy", "bootstrap_from_env",
    "enabled", "gather", "host_count", "host_of_id", "hosts",
    "hybrid_stripe_mesh", "initialize", "is_initialized",
    "is_multiprocess", "local_addressable", "local_host",
    "membership_changed", "process_count", "process_index",
    "put_global", "topology_signature",
]

_lock = threading.Lock()
_initialized = False
_init_info: Dict[str, int] = {}


def enabled() -> bool:
    """CEPH_TPU_MULTIHOST=0 is the kill switch: no process group is
    ever joined, the topology reads single-host, and every mesh plan
    keys exactly as the single-process PR-9 path."""
    return flags.enabled("CEPH_TPU_MULTIHOST")


# ---------------------------------------------------------------------------
# Bootstrap (the one place jax.distributed may be initialized)
# ---------------------------------------------------------------------------


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_count: Optional[int] = None) -> bool:
    """Join (or create) the jax.distributed process group.  THE
    bootstrap seam: raw ``jax.distributed.initialize`` outside this
    function is flagged by the ``raw-process-group`` lint rule.

    Must run BEFORE the jax backend initializes (it selects the gloo
    CPU collectives the emulated multi-host path needs; on real pods
    the TPU runtime brings its own ICI/DCN transports).  Idempotent;
    returns True when a multi-process group is (already) up, False
    for single-process operation (disabled, nproc <= 1, or jax
    absent)."""
    global _initialized
    with _lock:
        if _initialized:
            return True
        if not enabled():
            return False
        coordinator = coordinator or flags.get(
            "CEPH_TPU_MULTIHOST_COORD")
        if num_processes is None:
            num_processes = flags.flag_int(
                "CEPH_TPU_MULTIHOST_NPROC")
        if process_id is None:
            process_id = flags.flag_int("CEPH_TPU_MULTIHOST_PID")
        if not coordinator or num_processes <= 1:
            return False
        if local_device_count is None:
            env = flags.get("CEPH_TPU_MULTIHOST_LOCAL_DEVICES")
            local_device_count = int(env) if env else None
        if local_device_count:
            xla_flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in xla_flags:
                os.environ["XLA_FLAGS"] = (
                    xla_flags
                    + " --xla_force_host_platform_device_count="
                    f"{local_device_count}").strip()
        import jax

        try:
            # the CPU backend's cross-process collectives (the
            # emulation transport); a no-op name on backends that
            # bring their own
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # pragma: no cover - older/newer jax
            pass
        # THE one sanctioned call (this module is the rule's exempt
        # seam): everywhere else raw-process-group flags it
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes, process_id=process_id)
        _initialized = True
        _init_info.update(nproc=int(num_processes),
                          pid=int(process_id))
        return True


def bootstrap_from_env() -> bool:
    """Worker-side entry: join the group described by the
    CEPH_TPU_MULTIHOST_* env (set by the meshbench ``--processes``
    driver / a pod launcher); False when the env names no group."""
    return initialize()


def is_initialized() -> bool:
    return _initialized


def is_multiprocess() -> bool:
    return _initialized and process_count() > 1


def process_count() -> int:
    if not _initialized:
        return 1
    import jax

    return int(jax.process_count())


def process_index() -> int:
    if not _initialized:
        return 0
    import jax

    return int(jax.process_index())


# ---------------------------------------------------------------------------
# Host topology (the failure-domain map)
# ---------------------------------------------------------------------------

_topo_lock = threading.Lock()
_topo_cache: Optional[Tuple[str, Dict[int, int],
                            Tuple[Tuple[int, Tuple[int, ...]], ...]]] \
    = None


def _emulated_hosts() -> int:
    """CEPH_TPU_MULTIHOST_HOSTS=H partitions a SINGLE process's
    devices into H emulated hosts (index blocks) — the hermetic way
    tier-1 exercises host-level failure domains.  Ignored in a real
    multi-process group (processes ARE the hosts there)."""
    try:
        return max(flags.flag_int("CEPH_TPU_MULTIHOST_HOSTS"), 1)
    except ValueError:
        return 1


def _topology() -> Tuple[Dict[int, int],
                         Tuple[Tuple[int, Tuple[int, ...]], ...]]:
    """(device id -> host, ((host, (ids...)), ...)) — memoized on the
    config that shapes it (the device list itself is stable for a
    process's lifetime; breakers, not topology, carry health)."""
    global _topo_cache
    key = (f"{_initialized}/{_emulated_hosts()}/"
           f"{flags.get('CEPH_TPU_MULTIHOST')}")
    with _topo_lock:
        if _topo_cache is not None and _topo_cache[0] == key:
            return _topo_cache[1], _topo_cache[2]
    by_id: Dict[int, int] = {}
    try:
        import jax

        devs = list(jax.devices())
    except Exception:
        devs = []
    if _initialized:
        for d in devs:
            by_id[d.id] = int(d.process_index)
    elif enabled() and _emulated_hosts() > 1 and devs:
        h = _emulated_hosts()
        per = max(len(devs) // h, 1)
        for i, d in enumerate(devs):
            by_id[d.id] = min(i // per, h - 1)
    else:
        for d in devs:
            by_id[d.id] = 0
    groups: Dict[int, List[int]] = {}
    for did, host in by_id.items():
        groups.setdefault(host, []).append(did)
    sig = tuple(sorted((h, tuple(sorted(ids)))
                       for h, ids in groups.items()))
    with _topo_lock:
        _topo_cache = (key, by_id, sig)
    return by_id, sig


def host_of_id(device_id: int) -> int:
    """The host failure domain owning a device (0 when unknown —
    single-host operation never consults breakers beyond that)."""
    by_id, _ = _topology()
    return by_id.get(int(device_id), 0)


def hosts() -> Dict[int, Tuple[int, ...]]:
    """host -> its device ids (the whole cluster's view)."""
    _, sig = _topology()
    return {h: ids for h, ids in sig}


def host_count() -> int:
    _, sig = _topology()
    return max(len(sig), 1)


def local_host() -> int:
    """The host THIS process's code runs on (its own failure
    domain): the process index in a real group, host 0 under
    emulation (every emulated host is locally addressable)."""
    return process_index() if _initialized else 0


def local_addressable(host: int) -> bool:
    """True when this process can device_put to the host's devices
    (probe locally); a real remote host is reachable only through
    `agree()`."""
    if not _initialized:
        return True
    return host == process_index()


def topology_signature() -> tuple:
    """The process-topology element of a mesh ExecPlan key: process
    count + per-process (or emulated-host) device-set signature.  ()
    for the trivial single-host shape, so single-process plan keys
    stay bit-identical to the PR-9 form (the key-stability test's
    contract)."""
    _, sig = _topology()
    if len(sig) <= 1:
        return ()
    return (len(sig), sig)


# ---------------------------------------------------------------------------
# Hybrid meshes (DCN across hosts x ICI/dp within)
# ---------------------------------------------------------------------------


def hybrid_stripe_mesh(devices: Sequence):
    """A mesh for stripe-parallel EC work over `devices`: hosts on a
    "dcn" axis, each host's chips on "dp" — the
    create_hybrid_device_mesh shape, built by hand because the
    emulated topology has no ICI coordinates.  Falls back to a flat
    ("dp",) mesh when the set sits on one host or the per-host counts
    are ragged (a shrunken survivor set keeps dispatching either
    way); the logical axis rules map `stripe` across ("dcn", "dp"),
    so both shapes serve the same kernels."""
    import numpy as np
    from jax.sharding import Mesh

    devs = list(devices)
    by_host: Dict[int, List] = {}
    for d in devs:
        by_host.setdefault(host_of_id(d.id), []).append(d)
    counts = {len(v) for v in by_host.values()}
    if len(by_host) <= 1 or len(counts) != 1:
        return Mesh(np.asarray(devs), axis_names=("dp",))
    rows = [by_host[h] for h in sorted(by_host)]
    arr = np.asarray(rows, dtype=object).reshape(
        len(rows), len(rows[0]))
    return Mesh(arr, axis_names=("dcn", "dp"))


def _trace_collective(op: str, kind: str, topic: str = "") -> None:
    """Runtime twin hook (analysis/interleave.py): records the
    caller's call site at every seam entry so the multi-process
    harness can assert runtime ⊆ static-site-map and per-process
    order congruence.  Unarmed, this is one env read."""
    if not (flags.get("CEPH_TPU_COLLECTIVE_TRACE") == "1"
            or flags.get("CEPH_TPU_COLLECTIVE_TRACE_FILE")):
        return
    from ceph_tpu.analysis import interleave

    # depth 4: _caller_site <- record_collective <- _trace_collective
    # <- seam fn <- the caller whose site the static map must contain
    interleave.record_collective(op, kind, topic, depth=4)


def put_global(arr, sharding):
    """Place a host batch onto a (possibly cross-process) mesh.  The
    SPMD contract of the multi-process data plane: every process
    holds the SAME logical batch and contributes its addressable
    shards; single-process this is exactly jax.device_put."""
    import jax

    if not is_multiprocess():
        return jax.device_put(arr, sharding)
    _trace_collective("put_global", "put-global")
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def gather(out):
    """Materialize a dispatch output on every host: identity/asarray
    single-process, a tiled process_allgather across the group (each
    process holds only its addressable output shards)."""
    import numpy as np

    if not is_multiprocess():
        return np.asarray(out)
    _trace_collective("gather", "gather")
    if isinstance(out, (tuple, list)):
        return tuple(gather(o) for o in out)
    if getattr(out, "is_fully_addressable", True):
        return np.asarray(out)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(out, tiled=True))


# ---------------------------------------------------------------------------
# Collective-safe agreement (the coordinator KV store, never a
# collective: a dead host must read as a timeout, not a wedge)
# ---------------------------------------------------------------------------


def _kv_client():
    from jax._src import distributed

    return distributed.global_state.client


def _agree_timeout_s() -> float:
    try:
        return flags.flag_float("CEPH_TPU_MULTIHOST_AGREE_TIMEOUT_S")
    except ValueError:
        return 10.0


def agree(topic: str, payload: str,
          timeout_s: Optional[float] = None) -> Dict[int, Optional[str]]:
    """Publish `payload` under `topic` and read every process's entry
    back: {process -> payload or None (timed out / unreachable)}.

    SPMD contract: every live process calls agree() with the same
    topic in the same dispatch order (topics must be unique per round
    — the caller carries an epoch).  A host that died simply never
    publishes; its None is the membership verdict.  Single-process:
    {0: payload} without touching any service."""
    if not is_multiprocess():
        return {0: payload}
    _trace_collective("agree", "agreement", topic)
    client = _kv_client()
    pid = process_index()
    timeout_ms = int((timeout_s if timeout_s is not None
                      else _agree_timeout_s()) * 1000)
    try:
        client.key_value_set(f"ceph_tpu/{topic}/{pid}", payload)
    except Exception:
        pass  # duplicate publish on a retried round: the value stands
    out: Dict[int, Optional[str]] = {}
    for p in range(process_count()):
        if p == pid:
            out[p] = payload
            continue
        try:
            out[p] = client.blocking_key_value_get(
                f"ceph_tpu/{topic}/{p}", timeout_ms)
        except Exception:
            out[p] = None
    return out


def agree_healthy(local_healthy_ids: Sequence[int],
                  epoch: int = 0,
                  timeout_s: Optional[float] = None
                  ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Cross-process healthy-set agreement: every process publishes
    the device ids IT observes healthy (its local breaker state);
    the agreed set is the union of live hosts' reports restricted to
    each host's own devices.  Returns (healthy ids, unreachable
    hosts).  Deterministic across processes — the property that lets
    every survivor build the same shrunken mesh.  `epoch` labels the
    agreement round; callers must pass a value that is IDENTICAL on
    every live process for the same round (agreed_healthy() derives
    it from the lockstep membership round — a per-process call count
    would desync topics and make lagging-but-live peers read as
    dead)."""
    if not is_multiprocess():
        return tuple(sorted(int(i) for i in local_healthy_ids)), ()
    _trace_collective("agree_healthy", "agreement", f"healthy/{epoch}")
    reports = agree(f"healthy/{epoch}",
                    json.dumps(sorted(int(i)
                                      for i in local_healthy_ids)),
                    timeout_s)
    owned = hosts()
    healthy: List[int] = []
    dead: List[int] = []
    for host, ids in sorted(owned.items()):
        rep = reports.get(host)
        if rep is None:
            dead.append(host)
            continue
        try:
            seen = set(json.loads(rep))
        except ValueError:
            dead.append(host)
            continue
        mine = [i for i in ids if i in seen]
        if not mine:
            # the host answered but owns zero healthy chips: its
            # whole failure domain is out of the mesh — the same ONE
            # host event as never answering (device complex down, NIC
            # up)
            dead.append(host)
            continue
        healthy.extend(mine)
    return tuple(sorted(healthy)), tuple(dead)


_member_lock = threading.Lock()
_member_round = 0          # bumped ONLY at SPMD-lockstep points
_member_cache: Optional[Tuple[int, Tuple[int, ...]]] = None


def agreed_healthy(local_healthy_ids: Sequence[int]
                   ) -> Tuple[int, ...]:
    """Memoized membership agreement.  One agreement runs per
    MEMBERSHIP ROUND — a counter bumped only by membership_changed(),
    which is called at SPMD-lockstep points (a mesh dispatch failure
    and its attribution run on every live process in the same order),
    so every process agrees under the same round topic.  A local view
    change between rounds (a chip's jittered backoff expiring is
    clock-local and NOT lockstep) never triggers a fresh agreement —
    it would desync round topics across processes and make
    lagging-but-live peers read as dead; instead the cached agreed
    set is filtered against the CURRENT local view for this process's
    OWN devices (dropping a locally-degraded chip is always safe;
    re-admitting one waits for the next lockstep round).  Hosts that
    never answer a round are RETIRED (one host:<id> breaker event) —
    membership loss IS host loss."""
    global _member_cache
    local = tuple(sorted(int(i) for i in local_healthy_ids))
    if not is_multiprocess():
        return local
    _trace_collective("agreed_healthy", "agreement")
    with _member_lock:
        round_ = _member_round
        cached = _member_cache
    mine = set(hosts().get(local_host(), ()))
    localset = set(local)
    if cached is not None and cached[0] == round_:
        return tuple(i for i in cached[1]
                     if i not in mine or i in localset)
    healthy, dead = agree_healthy(local, epoch=round_)
    if dead:
        from ceph_tpu.common import circuit

        for h in dead:
            if not circuit.host_degraded(h):
                circuit.retire_host(h)
    with _member_lock:
        _member_cache = (round_, healthy)
    return healthy


def membership_changed() -> None:
    """Advance the membership round: the next healthy-set derivation
    re-agrees under the new round topic.  MUST be called only at
    SPMD-lockstep points (dispatch-failure attribution) so every
    live process advances together and agreement topics never
    desync."""
    global _member_round, _member_cache
    with _member_lock:
        _member_round += 1
        _member_cache = None

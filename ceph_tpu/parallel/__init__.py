"""Multi-chip parallelism: device meshes, sharded EC, sequence-parallel CRC.

The reference scales with threads + NCCL-free TCP messengers
(src/msg/async/); the TPU-native equivalent is a jax.sharding.Mesh whose
axes carry the framework's two parallel dimensions:

  - "dp" (data parallel): independent stripes/objects — Ceph's
    many-PGs-many-objects concurrency;
  - "sp" (sequence parallel): the byte axis of a stripe — Ceph's striping
    of one large object across OSDs (SURVEY.md §5.7), here striped across
    chips with XLA collectives over ICI doing the cross-shard math
    (CRC combine; gather for reconstruction).
"""

from ceph_tpu.parallel.mesh import make_mesh  # noqa: F401
from ceph_tpu.parallel.striped import (  # noqa: F401
    ShardedPipeline,
)

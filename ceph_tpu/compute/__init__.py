"""Coded compute: a straggler-tolerant compute-over-shards subsystem.

ROADMAP item 5 — the compute-in-storage workload class: batched,
vmap-able kernels (filter/aggregate pushdown, checksum and
compression-candidate scoring, embedding dot-product scoring) run ON
the OSDs that hold an object's erasure-coded shards, and the client
receives only tiny result bytes — the payload never crosses the wire.

The load-bearing idea (arXiv:2409.01420 "Erasure Coded Neural Network
Inference via Fisher Averaging", arXiv:1804.10331 rateless coded
matmul): a kernel that is GF(2^8)-LINEAR over byte positions commutes
with the erasure code.  Every coded shard satisfies
``c_j = sum_i G[j,i] * d_i`` position-wise, so for a linear kernel f,
``f(c_j) = sum_i G[j,i] * f(d_i)`` — the SAME code relation, on
R-byte results instead of chunk-size payloads.  The primary therefore
needs only the FIRST k shard-results (any k, hedged exactly like a
first-k read — osd/hedge.py), and decodes in the RESULT DOMAIN: a
tiny GF combine of k R-byte vectors through the very same
``ec_util.decode`` machinery the data path uses, with a synthetic
StripeInfo whose chunk size is the kernel's lane count.  A straggling
or dead OSD never blocks the scan.

Kernels that are NOT GF-linear (record aggregates, predicate scans,
entropy scoring, float dot products) cannot ride the code: they take
the FULL-DECODE FALLBACK — the primary reconstructs the object
through the normal hedged first-k read path and evaluates the kernel
on the logical bytes.  Still a pushdown (result bytes, not payload
bytes, cross the client wire), but the compute itself is only as
straggler-tolerant as the read under it.  The registry records which
family each kernel is in (`linear`), and the OSD engine picks the
path per (kernel, codec).

Registry: the plugin_registry pattern EC/compressor/cls already use —
kernels are named entries in a module-level table; `default_kernels`
registers the in-tree set.

Kill switch: CEPH_TPU_COMPUTE=0 — clients fall back to
read-then-compute with the same kernel reference implementations,
bit-exactly (the parity leg tests/test_compute_cluster.py drives).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from ceph_tpu.common import flags

ENOENT = -2
EINVAL = -22
EIO = -5

#: result width (bytes) of the linear kernels: small enough that a
#: 10k-object scan's results fit one frame, wide enough that the
#: fold/fingerprint collision bound is cryptographically irrelevant
#: for scrub-grade integrity scoring
DEFAULT_LANES = 32


def env_enabled() -> bool:
    """CEPH_TPU_COMPUTE=0 restores client-side read-then-compute."""
    return flags.enabled("CEPH_TPU_COMPUTE")


class ComputeError(Exception):
    """Raised by kernels to return an error rc for one object."""

    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc


def canon_json(obj: Any) -> bytes:
    """Canonical JSON result encoding: byte-identical across the
    pushdown, fallback, and client-side paths (the bit-exactness
    contract is on these bytes)."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def pad_to(data, multiple: int):
    """Zero-pad a byte stream up to a multiple (zeros are the GF
    additive identity, so linear kernel results are pad-invariant).
    Unpadded inputs pass through as views; a pad is the one honest
    copy, handed out as a readonly view."""
    from ceph_tpu.common.buffer import as_buffer

    buf = as_buffer(data)
    short = -len(buf) % multiple
    if short == 0:
        return buf
    out = bytearray(len(buf) + short)
    out[: len(buf)] = buf
    return memoryview(out).toreadonly()


def data_shard_streams(data, k: int, chunk: int) -> List:
    """Split padded logical bytes into the k data-shard chunk streams
    (the ECUtil interleave: stripe s of shard i is
    data[s*width + i*chunk : s*width + (i+1)*chunk]) — the host-side
    twin of what the OSDs hold, for oracles and fallbacks.  Each
    stream is one strided->contiguous gather handed out as a frozen
    buffer view (no second whole-stream copy)."""
    if k <= 1:
        return [pad_to(data, max(chunk, 1))]
    width = k * chunk
    padded = pad_to(data, width)
    arr = np.frombuffer(padded, dtype=np.uint8)
    # (stripes, k, chunk) -> per-shard concatenated chunk streams
    cube = arr.reshape(-1, k, chunk)
    out = []
    for i in range(k):
        stream = np.ascontiguousarray(cube[:, i, :]).reshape(-1)
        stream.setflags(write=False)
        out.append(stream.data)
    return out


class ComputeKernel:
    """One registered compute kernel.

    linear=True kernels are GF(2^8)-linear maps of the byte stream
    (result[r] = GF-sum over rows j of row_weights[j] * x[j*lanes+r]),
    evaluated per SHARD on the OSDs and combined in the result domain;
    their object-level answer is the GF-sum (XOR) of the k data-shard
    results.  linear=False kernels define `eval_object` on the
    reconstructed logical bytes.

    approx_capable=True marks a NONLINEAR kernel that can still run
    per-shard with an approximate result-domain combine (the Fisher
    fusion seam, ceph_tpu/inference/): the OSD pushdown and
    sub-compute paths admit `linear or approx_capable` kernels and
    call `shard_eval` — which such kernels override — instead of
    assuming the GF batched eval.  qos_class names the mClock class
    the per-shard eval is charged to, so inference work is shaped by
    its own dmClock profile rather than riding the compute class."""

    name = ""
    linear = False
    approx_capable = False
    qos_class = "compute"
    lanes = DEFAULT_LANES

    # -- common ------------------------------------------------------------

    def validate_args(self, args: Dict[str, Any]) -> None:
        """Raise ComputeError(EINVAL) on malformed args."""

    def reference(self, data, args: Dict[str, Any],
                  k: int = 1, chunk: int = 0) -> bytes:
        """Host oracle on the logical object bytes: the bit-exactness
        anchor every execution path (pushdown, full-decode fallback,
        client-side kill switch) must match."""
        if not self.linear:
            return self.eval_object(data, args)
        streams = data_shard_streams(data, k, chunk or self.lanes)
        return self.combine([self.eval_stream(s) for s in streams])

    # -- nonlinear surface -------------------------------------------------

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        raise NotImplementedError

    # -- linear surface ----------------------------------------------------

    def row_weights(self, rows: int) -> np.ndarray:
        """(1, rows) uint8 GF weight row: the kernel IS this matrix
        (result = weights @ reshaped stream, a GF matmul — which is
        why it rides the plan cache)."""
        raise NotImplementedError

    def eval_stream(self, stream) -> bytes:
        """Host evaluation of one shard chunk stream -> lanes bytes.
        The device path lives in `shard_eval_batch` (one plan-cached
        dispatch for a whole wave of shards); this is its bit-exact
        oracle and fallback."""
        from ceph_tpu.compute import kernels as _k

        padded = pad_to(stream, self.lanes)
        rows = len(padded) // self.lanes
        if rows == 0:
            return b"\x00" * self.lanes
        arr = np.frombuffer(padded, dtype=np.uint8).reshape(
            1, rows, self.lanes)
        out = _k.host_eval(self.row_weights(rows), arr)
        # lane-width result (32 B), not a payload copy
        return out[0, 0].tobytes()  # lint: disable=hot-path-copy

    def combine(self, parts: Sequence[bytes]) -> bytes:
        """GF-sum (XOR) of per-data-shard results -> the object-level
        answer."""
        acc = np.zeros(self.lanes, dtype=np.uint8)
        for p in parts:
            acc ^= np.frombuffer(p, dtype=np.uint8)
        # lane-width result (32 B), not a payload copy
        return acc.tobytes()  # lint: disable=hot-path-copy

    # -- per-shard surface (linear AND approx_capable) ---------------------

    def shard_eval(self, payloads: Sequence,
                   args: Dict[str, Any]) -> List[bytes]:
        """Evaluate a wave of locally-held shard payloads -> one
        result blob each.  Linear kernels get the batched plan-cached
        GF eval for free; approx_capable kernels override with their
        own per-shard forward (ceph_tpu/inference/kernels.py)."""
        if not self.linear:
            raise NotImplementedError(
                f"kernel {self.name} has no per-shard evaluation")
        return shard_eval_batch(self, payloads, args)


# ---------------------------------------------------------------------------
# Registry (plugin_registry pattern)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, ComputeKernel] = {}


def register(kernel: ComputeKernel) -> ComputeKernel:
    assert kernel.name and kernel.name not in _REGISTRY, kernel.name
    _REGISTRY[kernel.name] = kernel
    return kernel


def get_kernel(name: str) -> Optional[ComputeKernel]:
    _ensure_defaults()
    return _REGISTRY.get(name)


def registered_kernels() -> Dict[str, ComputeKernel]:
    _ensure_defaults()
    return dict(_REGISTRY)


def linear_kernels() -> Dict[str, ComputeKernel]:
    return {n: k for n, k in registered_kernels().items() if k.linear}


_defaults_loaded = False


def _ensure_defaults() -> None:
    global _defaults_loaded
    if _defaults_loaded:
        return
    _defaults_loaded = True
    from ceph_tpu.compute import kernels as _k

    _k.register_defaults(register)
    # the inference subsystem registers its approx_capable kernels
    # through the same seam (satellite of the dot_score gating fix)
    from ceph_tpu.inference import kernels as _ik

    _ik.register_defaults(register)


def shard_eval_batch(kernel: ComputeKernel, payloads: Sequence,
                     args: Dict[str, Any]) -> List[bytes]:
    """Evaluate a linear kernel over a WAVE of shard payloads in as
    few device dispatches as the length mix allows: payloads sharing a
    padded row count stack into ONE (B, rows, lanes) batch through the
    plan cache's `compute` kind (ec/plan.py), and a failed/absent
    device tier degrades to the bit-exact host path per group."""
    from ceph_tpu.compute import kernels as _k

    lanes = kernel.lanes
    groups: Dict[int, List[int]] = {}
    padded: List[bytes] = []
    for i, p in enumerate(payloads):
        buf = pad_to(p, lanes)
        padded.append(buf)
        groups.setdefault(len(buf), []).append(i)
    out: List[bytes] = [b""] * len(padded)
    for length, idxs in groups.items():
        rows = length // lanes
        if rows == 0:
            for i in idxs:
                out[i] = b"\x00" * lanes
            continue
        batch = np.stack([
            np.frombuffer(padded[i], dtype=np.uint8).reshape(
                rows, lanes)
            for i in idxs])
        weights = kernel.row_weights(rows)
        res = _k.planned_eval(kernel.name, weights, batch,
                              sig=_k.weights_sig(kernel, rows))
        for row, i in enumerate(idxs):
            # lane-width result (32 B), not a payload copy
            out[i] = res[row, 0].tobytes()  # lint: disable=hot-path-copy
    return out

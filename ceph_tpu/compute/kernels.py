"""The in-tree compute kernels.

Two families (see ceph_tpu/compute/__init__.py for the theory):

GF-linear (pushdown to the coded shards, first-k result-domain
decode):

- ``gf_fold``        R-lane GF(2^8) fold (XOR of every lane-strided
                     byte): the checksum-pushdown kernel — a content
                     digest of the whole object computed without
                     moving it.
- ``gf_fingerprint`` seeded GF-weighted fold: a position-sensitive
                     content fingerprint (dedup candidate scoring) —
                     unlike the plain fold it detects chunk
                     permutations, because every lane-row carries its
                     own GF weight.

Nonlinear (per-kernel `approx_capable` decides the path: False means
the full-decode fallback at the primary, True means per-shard
pushdown with a result-domain approximate combine — the seam the
inference engine's kernels register through, ceph_tpu/inference/;
either way results, not payloads, cross the client wire):

- ``count``/``sum``/``min``/``max``  aggregate pushdown over
                     fixed-width records with an optional predicate
                     on a little-endian field.
- ``filter``         predicate scan: matching record indices
                     (bounded) + total match count.
- ``compress_score`` order-0 entropy estimate (bits/byte) over
                     fixed blocks — the compression-candidate scoring
                     of compressor/scoring.py, run where the data
                     lives.
- ``dot_score``      embedding scoring: object bytes as float32
                     vectors, best dot-product match against the
                     query vector in args.

Raw-dispatch discipline: ``device_eval`` is the ONE jax kernel body;
it must only run through the plan cache (ec/plan.py `compute` kind,
via ``planned_eval``) or inside circuit.device_call — the
`unplanned-compute-dispatch` lint rule enforces it.  ``host_eval`` is
the bit-exact numpy twin used by oracles and the degraded path.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from ceph_tpu.common.buffer import as_buffer
from ceph_tpu.compute import (
    ComputeError, ComputeKernel, DEFAULT_LANES, EINVAL, canon_json,
)

#: seed of the fingerprint kernel's GF weight stream (a protocol
#: constant: every daemon and every client oracle must derive the
#: same weights)
FINGERPRINT_SEED = 0xCE9


def make_device_eval(weights: np.ndarray):
    """Build THE traced device kernel body for one weight row: a
    row-weighted XOR fold of the (B, rows, lanes) shard batch —
    GF(2^8) scalar products via the log/exp field tables, XOR
    reduction over rows.  This is the fold SHAPE of the linear
    kernels; the generic bit-matrix matmul would pay an 8x bitplane
    expansion to express the same reduction.  All-ones weights (the
    gf_fold kernel) lower to a pure XOR reduce.

    The returned callable must only be invoked through ec/plan.py's
    `compute` plan kind (tracked_jit + breaker guard) — the
    `unplanned-compute-dispatch` lint rule flags raw calls."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import gf

    w = np.asarray(weights, dtype=np.uint8).reshape(-1)

    def xor_rows(arr):
        return jax.lax.reduce(arr, np.uint8(0),
                              jax.lax.bitwise_xor, (1,))

    if (w == 1).all():
        def device_eval_fold(data):
            return xor_rows(data)[:, None, :]

        return device_eval_fold

    lw = jnp.asarray(gf.GF_LOG[w])
    nzw = jnp.asarray(w != 0)
    exp = jnp.asarray(gf.GF_EXP)
    log = jnp.asarray(gf.GF_LOG)

    def device_eval_weighted(data):
        # exact jnp twin of gf.gf_mul's table math (bit-exactness
        # contract with host_eval below)
        prod = exp[log[data] + lw[None, :, None]]
        prod = jnp.where((data == 0) | ~nzw[None, :, None],
                         np.uint8(0), prod)
        return xor_rows(prod)[:, None, :]

    return device_eval_weighted


def host_eval(weights: np.ndarray, batch: np.ndarray) -> np.ndarray:
    """Bit-exact numpy twin: (1, rows) GF weights x (B, rows, lanes)
    -> (B, 1, lanes) via the same table math.  The oracle for every
    device result and the degraded path when the device tier is
    absent or its breaker is open."""
    w = np.asarray(weights, dtype=np.uint8).reshape(-1)
    b = np.ascontiguousarray(batch)
    if (w == 1).all():
        out = np.bitwise_xor.reduce(b, axis=1)
    else:
        from ceph_tpu.ops import gf

        out = np.bitwise_xor.reduce(
            gf.gf_mul(w[None, :, None], b), axis=1)
    return out[:, None, :]


def planned_eval(name: str, weights: np.ndarray,
                 batch: np.ndarray,
                 sig: str = None) -> np.ndarray:
    """One wave's kernel evaluation through the plan cache: the
    `compute` plan kind dispatches device-side under the ``compute``
    breaker family; None (no backend / open breaker / quarantined
    plan) degrades to the bit-exact host path.  `sig` is the weight
    row's content signature (weights_sig memoizes it — re-hashing a
    64 Ki-row weight stream per dispatch is pure waste)."""
    from ceph_tpu.ec import plan as ec_plan

    out = ec_plan.compute_eval(name, weights, batch, sig=sig)
    if out is None:
        out = host_eval(weights, batch)
    return np.asarray(out)


_SIG_CACHE: Dict[tuple, str] = {}


def weights_sig(kernel, rows: int) -> str:
    """Memoized plan-key signature of a kernel's (name, rows) weight
    row — pure function of both, so the hash runs once per geometry,
    not once per wave."""
    key = (kernel.name, rows)
    hit = _SIG_CACHE.get(key)
    if hit is None:
        from ceph_tpu.ec import plan as ec_plan

        hit = ec_plan.matrix_signature(
            np.asarray(kernel.row_weights(rows), dtype=np.uint8),
            extra=f"compute/{kernel.name}")
        if len(_SIG_CACHE) > 256:
            _SIG_CACHE.clear()
        _SIG_CACHE[key] = hit
    return hit


# ---------------------------------------------------------------------------
# Linear kernels
# ---------------------------------------------------------------------------


class GfFold(ComputeKernel):
    """R-lane GF fold: result[r] = XOR of bytes at positions == r
    (mod lanes).  All-ones weight row — the pure checksum kernel."""

    name = "gf_fold"
    linear = True
    lanes = DEFAULT_LANES

    def row_weights(self, rows: int) -> np.ndarray:
        return np.ones((1, rows), dtype=np.uint8)


class GfFingerprint(ComputeKernel):
    """Seeded GF-weighted fold: row j carries a deterministic nonzero
    GF weight, so permuted content folds differently (the dedup /
    content-addressing fingerprint).  Weight stream is a protocol
    constant derived from FINGERPRINT_SEED."""

    name = "gf_fingerprint"
    linear = True
    lanes = DEFAULT_LANES

    def __init__(self):
        # memoized per row count: the stream is a deterministic
        # protocol constant, and a 10k-object scan would otherwise
        # regenerate it once per length-group per wave per OSD.
        # (Full regeneration per rows value, never prefix-slicing a
        # longer stream: numpy's bounded-integer generation is not
        # prefix-stable across lengths.)
        self._weights_cache: Dict[int, np.ndarray] = {}

    def row_weights(self, rows: int) -> np.ndarray:
        hit = self._weights_cache.get(rows)
        if hit is None:
            rng = np.random.default_rng(FINGERPRINT_SEED)
            # nonzero GF weights: zero rows would blind the
            # fingerprint
            hit = rng.integers(1, 256, (1, rows), dtype=np.uint8) \
                if rows else np.ones((1, 0), dtype=np.uint8)
            hit.setflags(write=False)
            if len(self._weights_cache) > 16:
                self._weights_cache.clear()
            self._weights_cache[rows] = hit
        return hit


# ---------------------------------------------------------------------------
# Nonlinear kernels: record aggregates / predicate scan
# ---------------------------------------------------------------------------

_CMPS = {
    "eq": np.equal, "ne": np.not_equal,
    "lt": np.less, "le": np.less_equal,
    "gt": np.greater, "ge": np.greater_equal,
}


def _int_arg(args: Dict[str, Any], key: str, default: int) -> int:
    """Client-supplied JSON -> int, or ComputeError(EINVAL): args
    come off the wire, so a null/string/huge value must surface as
    the op's rc, never as a TypeError inside the engine."""
    raw = args.get(key, default)
    try:
        val = int(raw)
    except (TypeError, ValueError):
        raise ComputeError(EINVAL, f"bad {key}={raw!r}")
    if not -(1 << 63) <= val < (1 << 64):
        raise ComputeError(EINVAL, f"{key} out of range")
    return val


def _record_fields(data, args: Dict[str, Any]):
    """(field values uint64, match mask) for the record-aggregate
    family: fixed-width records, little-endian unsigned field at
    [off, off+len), optional predicate {"cmp", "value"}."""
    rsize = _int_arg(args, "record", 8)
    off = _int_arg(args, "off", 0)
    flen = _int_arg(args, "len", min(8, max(rsize - off, 1)))
    if rsize <= 0 or off < 0 or flen <= 0 or flen > 8 or \
            off + flen > rsize:
        raise ComputeError(EINVAL, "bad record/field spec")
    buf = as_buffer(data)
    nrec = len(buf) // rsize
    arr = np.frombuffer(buf, dtype=np.uint8,
                        count=nrec * rsize).reshape(nrec, rsize)
    weights = (1 << (8 * np.arange(flen, dtype=np.uint64)))
    fields = arr[:, off:off + flen].astype(np.uint64) @ weights
    cmp = args.get("cmp")
    if cmp is None:
        return fields, np.ones(nrec, dtype=bool)
    fn = _CMPS.get(str(cmp))
    if fn is None:
        raise ComputeError(EINVAL, f"unknown cmp {cmp!r}")
    value = _int_arg(args, "value", 0)
    if value < 0:
        raise ComputeError(EINVAL, "value must be unsigned")
    return fields, fn(fields, np.uint64(value))


class RecordAgg(ComputeKernel):
    """count/sum/min/max over a record field, optionally predicated —
    the filter/aggregate pushdown family (one class, one reducer per
    registered name)."""

    linear = False

    def __init__(self, name: str):
        self.name = name

    def validate_args(self, args: Dict[str, Any]) -> None:
        _record_fields(b"", args)

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        fields, mask = _record_fields(data, args)
        hit = fields[mask]
        if self.name == "count":
            return canon_json({"count": int(mask.sum())})
        if self.name == "sum":
            return canon_json({"count": int(mask.sum()),
                               "sum": int(hit.sum(dtype=np.uint64))
                               if hit.size else 0})
        val = None
        if hit.size:
            val = int(hit.min() if self.name == "min" else hit.max())
        return canon_json({"count": int(mask.sum()), self.name: val})


class FilterScan(ComputeKernel):
    """Predicate scan: total matches + the first `limit` matching
    record indices (the pgls-of-records shape)."""

    name = "filter"
    linear = False

    def validate_args(self, args: Dict[str, Any]) -> None:
        _record_fields(b"", args)

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        _fields, mask = _record_fields(data, args)
        limit = max(0, min(_int_arg(args, "limit", 1024), 65536))
        idx = np.flatnonzero(mask)
        return canon_json({"count": int(idx.size),
                           "indices": [int(i) for i in idx[:limit]]})


class CompressScore(ComputeKernel):
    """Compression-candidate scoring: order-0 entropy (bits/byte)
    over fixed blocks via compressor/scoring.py's histogram path —
    incompressible objects (entropy near 8) can skip the codec
    entirely, decided where the bytes already are."""

    name = "compress_score"
    linear = False

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        from ceph_tpu.compressor import scoring

        block = _int_arg(args, "block", 4096)
        if block <= 0:
            raise ComputeError(EINVAL, "bad block")
        buf = as_buffer(data)
        if len(buf) == 0:
            return canon_json({"blocks": 0, "entropy_bpb": 0.0})
        nfull = max(len(buf) // block, 1)
        span = min(len(buf), nfull * block)
        blocks = np.frombuffer(buf, dtype=np.uint8,
                               count=(span // nfull) * nfull)
        blocks = blocks.reshape(nfull, -1)
        ent = scoring.entropy_bits_per_byte_host(blocks)
        return canon_json({
            "blocks": int(nfull),
            "entropy_bpb": round(float(np.mean(ent)), 4)})


class DotScore(ComputeKernel):
    """Embedding scoring: the object is a run of float32 vectors of
    dimension args["dim"]; score each against args["query"] and
    return the best match — inference-adjacent pushdown (the
    arXiv:2409.01420 workload shape)."""

    name = "dot_score"
    linear = False
    # argmax over raw object bytes has no per-shard decomposition:
    # NOT approx-capable, so it keeps the full-decode path.  The
    # coded serving of this workload shape lives in
    # ceph_tpu/inference/ (Fisher-fused shards, `infer` kernel),
    # whose kernels set approx_capable=True through this same seam.
    approx_capable = False

    def validate_args(self, args: Dict[str, Any]) -> None:
        dim = _int_arg(args, "dim", 0)
        query = args.get("query")
        if dim <= 0 or not isinstance(query, (list, tuple)) or \
                len(query) != dim:
            raise ComputeError(EINVAL, "dot_score needs dim + query")

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        self.validate_args(args)
        dim = _int_arg(args, "dim", 0)
        try:
            q = np.asarray(args["query"], dtype=np.float32)
        except (TypeError, ValueError):
            raise ComputeError(EINVAL, "bad query vector")
        buf = as_buffer(data)
        stride = 4 * dim
        n = len(buf) // stride
        if n == 0:
            return canon_json({"n": 0, "best": None, "score": None})
        emb = np.frombuffer(buf, dtype=np.float32,
                            count=n * dim).reshape(n, dim)
        scores = emb @ q
        best = int(np.argmax(scores))
        return canon_json({"n": n, "best": best,
                           "score": round(float(scores[best]), 4)})


def register_defaults(register) -> None:
    """Register the in-tree kernel set (the default_handler role)."""
    register(GfFold())
    register(GfFingerprint())
    for name in ("count", "sum", "min", "max"):
        register(RecordAgg(name))
    register(FilterScan())
    register(CompressScore())
    register(DotScore())


def parse_args(raw: str) -> Dict[str, Any]:
    """Wire args (JSON text) -> dict; '' means {}."""
    if not raw:
        return {}
    try:
        out = json.loads(raw)
    except ValueError:
        raise ComputeError(EINVAL, "args not JSON")
    if not isinstance(out, dict):
        raise ComputeError(EINVAL, "args must be an object")
    return out

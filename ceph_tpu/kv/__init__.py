"""KeyValueDB: the metadata store abstraction.

Reference parity: KeyValueDB (/root/reference/src/kv/KeyValueDB.h) — a
prefix(column-family)-organized KV store with atomic write batches and
ordered iteration, backed by RocksDB in the reference.  Backends here:
MemDB (tests; reference src/kv/MemDB) and SQLiteDB (the persistent
RocksDB-role backend — sqlite3 is the battle-tested embedded KV engine in
this image; WAL-mode journaling plays RocksDB's WAL role).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class Transaction:
    """A write batch: applied atomically by submit_transaction."""

    def __init__(self) -> None:
        self.ops: List[Tuple[str, str, bytes, Optional[bytes]]] = []

    def set(self, prefix: str, key: bytes, value: bytes) -> None:
        self.ops.append(("set", prefix, bytes(key), bytes(value)))

    def rmkey(self, prefix: str, key: bytes) -> None:
        self.ops.append(("rm", prefix, bytes(key), None))

    def rmkeys_by_prefix(self, prefix: str) -> None:
        self.ops.append(("rm_prefix", prefix, b"", None))

    def rm_range_keys(self, prefix: str, start: bytes, end: bytes) -> None:
        """Delete keys in [start, end)."""
        self.ops.append(("rm_range", prefix, bytes(start), bytes(end)))


class KeyValueDB:
    """Durability contract (what the crash model in os/faultstore.py
    assumes, and what SQLite WAL actually provides): batches are
    ATOMIC (never torn) and PREFIX-durable — a power cut may lose
    recently submitted batches, but only from the tail, never out of
    order.  `submit_transaction` survives process death;
    `submit_transaction_sync` is the power-cut barrier — it and every
    batch before it survive the plug being pulled.  Stores must place
    their commit point (the op that lets on_commit fire) behind the
    sync form."""

    def create_and_open(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def get_transaction(self) -> Transaction:
        return Transaction()

    def submit_transaction(self, t: Transaction) -> None:
        raise NotImplementedError

    def submit_transaction_sync(self, t: Transaction) -> None:
        self.submit_transaction(t)

    def get(self, prefix: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def get_iterator(self, prefix: str, start: bytes = b"",
                     end: Optional[bytes] = None
                     ) -> Iterator[Tuple[bytes, bytes]]:
        """Ordered (key, value) pairs in [start, end)."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    def __init__(self) -> None:
        self._data: Dict[str, Dict[bytes, bytes]] = {}
        self._lock = threading.Lock()

    def create_and_open(self) -> None:
        pass

    def submit_transaction(self, t: Transaction) -> None:
        with self._lock:
            for op, prefix, key, value in t.ops:
                table = self._data.setdefault(prefix, {})
                if op == "set":
                    table[key] = value
                elif op == "rm":
                    table.pop(key, None)
                elif op == "rm_prefix":
                    table.clear()
                elif op == "rm_range":
                    for k in [k for k in table if key <= k < value]:
                        del table[k]

    def get(self, prefix: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(prefix, {}).get(bytes(key))

    def get_iterator(self, prefix: str, start: bytes = b"",
                     end: Optional[bytes] = None):
        with self._lock:
            items = sorted(self._data.get(prefix, {}).items())
        for key, value in items:
            if key < start:
                continue
            if end is not None and key >= end:
                break
            yield key, value


class SQLiteDB(KeyValueDB):
    """RocksDB-role persistent backend (WAL journaling, atomic batches)."""

    def __init__(self, path: str):
        self.path = path
        self._conn: Optional[sqlite3.Connection] = None
        self._lock = threading.Lock()

    def create_and_open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " prefix TEXT NOT NULL, key BLOB NOT NULL, value BLOB,"
            " PRIMARY KEY (prefix, key))")
        self._conn.commit()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def submit_transaction(self, t: Transaction) -> None:
        with self._lock:
            cur = self._conn.cursor()
            for op, prefix, key, value in t.ops:
                if op == "set":
                    cur.execute(
                        "INSERT OR REPLACE INTO kv (prefix, key, value)"
                        " VALUES (?, ?, ?)", (prefix, key, value))
                elif op == "rm":
                    cur.execute(
                        "DELETE FROM kv WHERE prefix = ? AND key = ?",
                        (prefix, key))
                elif op == "rm_prefix":
                    cur.execute("DELETE FROM kv WHERE prefix = ?",
                                (prefix,))
                elif op == "rm_range":
                    cur.execute(
                        "DELETE FROM kv WHERE prefix = ? AND key >= ?"
                        " AND key < ?", (prefix, key, value))
            self._conn.commit()

    def submit_transaction_sync(self, t: Transaction) -> None:
        """Really-durable commit: synchronous=FULL for this transaction
        so a machine crash cannot forget state a caller already
        published (the mon's Paxos-commit requirement AND TPUStore's
        transaction commit point; WAL+NORMAL only survives process
        death)."""
        with self._lock:
            self._conn.execute("PRAGMA synchronous=FULL")
            try:
                cur = self._conn.cursor()
                for op, prefix, key, value in t.ops:
                    if op == "set":
                        cur.execute(
                            "INSERT OR REPLACE INTO kv"
                            " (prefix, key, value) VALUES (?, ?, ?)",
                            (prefix, key, value))
                    elif op == "rm":
                        cur.execute(
                            "DELETE FROM kv WHERE prefix = ?"
                            " AND key = ?", (prefix, key))
                    elif op == "rm_prefix":
                        cur.execute("DELETE FROM kv WHERE prefix = ?",
                                    (prefix,))
                    elif op == "rm_range":
                        cur.execute(
                            "DELETE FROM kv WHERE prefix = ?"
                            " AND key >= ? AND key < ?",
                            (prefix, key, value))
                self._conn.commit()
            finally:
                self._conn.execute("PRAGMA synchronous=NORMAL")

    def get(self, prefix: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE prefix = ? AND key = ?",
                (prefix, bytes(key))).fetchone()
        return row[0] if row else None

    def get_iterator(self, prefix: str, start: bytes = b"",
                     end: Optional[bytes] = None):
        with self._lock:
            if end is None:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE prefix = ? AND"
                    " key >= ? ORDER BY key", (prefix, bytes(start))
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE prefix = ? AND"
                    " key >= ? AND key < ? ORDER BY key",
                    (prefix, bytes(start), bytes(end))).fetchall()
        yield from rows

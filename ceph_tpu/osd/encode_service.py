"""Async micro-batching encode service for the OSD write path.

PR 2 made EC encode compile-once/dispatch-few (ec/plan.py), but every
client write still called `ec_util.encode_with_hinfo` synchronously,
one object at a time, on the asyncio event loop.  This service is the
missing layer between the cluster datapath and the batched kernels:
concurrent write handlers **await** their encodes here, requests
pool while a dispatch is in flight (an idle bucket dispatches
immediately — adaptive group commit, so the small-op band never
pays an accumulation wait it can't amortize; a ~1ms window and a
byte budget bound the pooling), then ONE flush dispatches the batch
through the plan-cached fused encode+crc path **off-loop**
(asyncio.to_thread, the event loop never blocks on the device) and
resolves each request's future with its own shards + hinfo CRCs.

Pipelining is double-buffered: each profile bucket holds two dispatch
slots, so while batch N computes on device, batch N+1 accumulates and
the sub-write network fan-out of already-completed ops overlaps the
next dispatch.

Mesh scale-out: each flush picks mesh vs single-device through the
plan cache (ec/plan.py) — a batch past the CEPH_TPU_MESH_MIN_BYTES /
_MIN_STRIPES gates shards stripe-parallel over the live healthy chip
mesh, and a sick chip shrinks the mesh (never degrades the flush to
host).  The `mesh_batches` counter reports how many flushes rode the
mesh.

Knobs (read at construction):

  CEPH_TPU_ENCODE_BATCH_WINDOW_MS  accumulation upper bound (the
                                   common path is the adaptive
                                   idle/completion flush), default 1.0
  CEPH_TPU_ENCODE_BATCH_BYTES      flush early once this many bytes
                                   are pending (default 8 MiB)
  CEPH_TPU_ENCODE_SERVICE=0        kill switch — every call runs the
                                   inline (pre-service) path, results
                                   and behavior unchanged from the
                                   un-batched daemon

Degradation policy: batching engages when a batched tier can — the
fused device tier (ec_util.device_fused_available) or, for the
bitmatrix family on the hinfo write path, the packed native XOR-tape
tier (ec_util.bitmatrix_native_available: N objects' regions pack
into ONE arena and the whole bucket executes as a single compiled
tape run, per-shard CRC ledger folded natively over arena spans).
On CPU-only runs with neither tier every request takes the inline
path, so existing behavior is untouched.  Backpressure is a bounded queue
per profile (requests + bytes, counting in-flight batches); overflow
**sheds to the inline path** instead of queueing unboundedly, so a
storm degrades to today's latency rather than deadlocking.

Threading: all bookkeeping (buckets, counters, histograms) runs on
the owning event loop; only the numeric batch body runs in the
to_thread worker, so no lock is needed.
"""

from __future__ import annotations

import asyncio
import os

from ceph_tpu.common import flags
import time
from typing import Dict, Iterable, List, Optional

from ceph_tpu.common import tracing
from ceph_tpu.osd import ec_util, scheduler

__all__ = ["EncodeService"]


def _env_float(name: str, default: float) -> float:
    try:
        return flags.flag_float(name, default)
    except ValueError:
        return default


def _pow2_bucket(n: int) -> int:
    """Histogram bucket for batch sizes: next power of two."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def _buf(d):
    """Pass buffers through to ec_util unchanged; materialize only
    non-buffer payloads.  The old `bytes(d)`-unless-bytes guard copied
    every memoryview payload once per inline encode — unnecessary: the
    write path snapshots caller-mutable buffers BEFORE the service
    sees them (`_op_write_full_locked`/`_op_write`), so a view here is
    already stable, and ec_util slices views zero-copy."""
    return d if isinstance(d, (bytes, bytearray, memoryview)) \
        else bytes(d)


_WAIT_EDGES_MS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0)


def _wait_bucket(seconds: float) -> str:
    ms = seconds * 1e3
    for edge in _WAIT_EDGES_MS:
        if ms <= edge:
            return f"<={edge}ms"
    return f">{_WAIT_EDGES_MS[-1]}ms"


class _Req:
    __slots__ = ("fut", "payload", "nbytes", "t_q", "span_ctx")

    def __init__(self, fut: asyncio.Future, payload, nbytes: int):
        self.fut = fut
        self.payload = payload
        self.nbytes = nbytes
        self.t_q = time.perf_counter()
        # the enqueuing op's span context: the batched flush span
        # LINKS to every op it served (N ops -> 1 device dispatch)
        span = tracing.current_span.get()
        self.span_ctx = span.context if span is not None else None


class _Bucket:
    """Accumulation queue for one (kind, codec profile, geometry)."""

    __slots__ = ("kind", "label", "sinfo", "codec", "pending",
                 "nbytes", "outstanding", "outstanding_bytes",
                 "timer", "sem", "stats", "in_flight", "tier",
                 "last_arrival", "ewma_gap")

    def __init__(self, kind: str, label: str, sinfo, codec,
                 tier: str = "device"):
        self.kind = kind
        self.label = label
        self.sinfo = sinfo
        self.codec = codec
        self.tier = tier
        self.pending: List[_Req] = []
        self.nbytes = 0
        self.outstanding = 0          # queued + in-flight requests
        self.outstanding_bytes = 0
        self.in_flight = 0            # dispatched batches not yet done
        # arrival-density tracking (the bitmatrix hot/cold router),
        # keyed by mClock class: a recovery wave's dense arrivals
        # must not mark the bucket hot for sparse client writes (and
        # a client trickle must not mask a forming recovery batch)
        self.last_arrival: Dict[str, float] = {}
        self.ewma_gap: Dict[str, float] = {}
        self.timer: Optional[asyncio.TimerHandle] = None
        # two dispatch slots: the double buffer — batch N on device,
        # batch N+1 accumulating/launching behind it
        self.sem = asyncio.Semaphore(2)
        self.stats: Dict[str, object] = {
            "requests": 0, "batches": 0, "dispatch_seconds": 0.0,
            "batch_size_hist": {}, "fill_pct_hist": {},
            "wait_ms_hist": {},
        }


class EncodeService:
    """Per-codec-profile micro-batching encode/decode front end."""

    def __init__(self, who: str = "osd",
                 window_ms: Optional[float] = None,
                 max_batch_bytes: Optional[int] = None,
                 max_queue_requests: int = 256,
                 max_queue_bytes: Optional[int] = None):
        self.who = who
        self.enabled = flags.enabled("CEPH_TPU_ENCODE_SERVICE")
        if window_ms is None:
            window_ms = _env_float("CEPH_TPU_ENCODE_BATCH_WINDOW_MS",
                                   1.0)
        self.window_s = max(float(window_ms), 0.0) / 1e3
        if max_batch_bytes is None:
            max_batch_bytes = int(_env_float(
                "CEPH_TPU_ENCODE_BATCH_BYTES", float(8 << 20)))
        self.max_batch_bytes = max(int(max_batch_bytes), 1)
        self.max_queue_requests = max(int(max_queue_requests), 1)
        self.max_queue_bytes = int(max_queue_bytes
                                   if max_queue_bytes is not None
                                   else 4 * self.max_batch_bytes)
        self._buckets: Dict[tuple, _Bucket] = {}
        self._tasks: set = set()
        self._closed = False
        # set by the owning daemon: flush dispatch spans (with links
        # to the ops each batch served) land in this tracer's ring
        self.tracer = None
        self._usable_cache: Dict[int, str] = {}
        self.counters = {"requests": 0, "batched": 0, "inline": 0,
                         "inline_cold": 0, "shed": 0, "batches": 0,
                         "dispatch_errors": 0, "device_fallback": 0,
                         "mesh_batches": 0}

    # -- public API (the daemon's awaited entry points) -------------------

    async def encode_with_hinfo(self, sinfo, codec, data,
                                want: Iterable[int],
                                logical_len: Optional[int] = None):
        """Awaitable twin of ec_util.encode_with_hinfo — identical
        results, but concurrent callers share device dispatches."""
        want = tuple(want)
        self.counters["requests"] += 1
        q = self._bucket_for("encode_hinfo", sinfo, codec)
        if q is not None and self._cold_inline(q):
            self.counters["inline_cold"] += 1
            q = None
        if q is None or not self._admit(q, len(data)):
            self.counters["inline" if q is None else "shed"] += 1
            # intentionally-inline path (kill switch, no batchable
            # tier, a cold bitmatrix bucket, or backpressure shed).
            # The span names the stage — inline codec work must be
            # attributable in the histograms (the xsched bench cites
            # it), not folded invisibly into osd_op self-time
            with tracing.child_span_sync("encode_inline"):
                return ec_util.encode_with_hinfo(
                    sinfo, codec, data, want, logical_len=logical_len)
        return await self._enqueue(q, (data, want, logical_len),
                                   len(data))

    async def encode(self, sinfo, codec, data,
                     want: Iterable[int]) -> Dict[int, bytes]:
        """Awaitable twin of ec_util.encode (plain shards, no hinfo:
        the RMW re-encode and recovery re-encode path)."""
        want = tuple(want)
        self.counters["requests"] += 1
        q = self._bucket_for("encode", sinfo, codec)
        if q is None or not self._admit(q, len(data)):
            self.counters["inline" if q is None else "shed"] += 1
            with tracing.child_span_sync("encode_inline"):
                return ec_util.encode(sinfo, codec, _buf(data), want)
        return await self._enqueue(q, (data, want), len(data))

    async def decode(self, sinfo, codec, to_decode) -> bytes:
        """Awaitable twin of ec_util.decode: concurrent reads and
        recovery reconstructions sharing a survivor set batch into one
        device dispatch (the decode_many service path)."""
        self.counters["requests"] += 1
        nbytes = sum(len(v) for v in to_decode.values())
        k = codec.get_data_chunk_count()
        # all data shards present = pure host interleave, no device
        # work to batch — keep it inline (the common read fast path)
        all_data = not codec.get_chunk_mapping() and \
            all(i in to_decode for i in range(k))
        q = None if all_data else self._bucket_for("decode", sinfo,
                                                   codec)
        if q is None or not self._admit(q, nbytes):
            self.counters["inline" if q is None else "shed"] += 1
            with tracing.child_span_sync("decode_inline"):
                return ec_util.decode(sinfo, codec, to_decode)
        return await self._enqueue(q, dict(to_decode), nbytes)

    async def decode_many(self, sinfo, codec, maps) -> list:
        """N decode requests at once (the recovery-wave entry):
        returns one outcome per request — the decoded bytes, or the
        Exception that request raised (callers isolate failures per
        object).  Batchable requests enqueue individually and group in
        the flush; the inline tier keeps today's one-host-fold-per-
        survivor-group behavior via ec_util.decode_many."""
        maps = list(maps)
        if not maps:
            return []
        q = self._bucket_for("decode", sinfo, codec)
        if q is not None:
            return await asyncio.gather(
                *(self.decode(sinfo, codec, m) for m in maps),
                return_exceptions=True)
        self.counters["requests"] += len(maps)
        self.counters["inline"] += len(maps)
        try:
            return ec_util.decode_many(sinfo, codec, maps)
        except Exception:
            outs: list = []
            for m in maps:
                try:
                    outs.append(ec_util.decode(sinfo, codec, m))
                except Exception as e:
                    outs.append(e)
            return outs

    async def stop(self) -> None:
        """Flush everything pending and await in-flight dispatches —
        every caller blocked on a future resolves (no deadlock);
        requests arriving after stop() run inline."""
        self._closed = True
        for q in list(self._buckets.values()):
            self._flush(q)
        while self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    def stats(self) -> dict:
        """Observability snapshot: aggregate counters, live queue
        depth, and per-profile batch-size / fill-ratio / wait-time
        histograms (the admin-socket `encode_service` command and the
        bench contract line surface this)."""
        return {
            "enabled": self.enabled,
            **self.counters,
            "queue_depth": sum(q.outstanding
                               for q in self._buckets.values()),
            "queue_bytes": sum(q.outstanding_bytes
                               for q in self._buckets.values()),
            "window_ms": self.window_s * 1e3,
            "max_batch_bytes": self.max_batch_bytes,
            "profiles": {q.label: {k: (dict(v) if isinstance(v, dict)
                                       else v)
                                   for k, v in q.stats.items()}
                         for q in self._buckets.values()},
        }

    # -- internals --------------------------------------------------------

    def _usable(self, codec) -> str:
        """The batching tier this codec can ride: "device" (fused
        encode+crc plan), "bitmatrix" (packed native XOR tape —
        ec_util._encode_many_bitmatrix), or "" (inline only)."""
        if not self.enabled or self._closed:
            return ""
        key = id(codec)
        hit = self._usable_cache.get(key)
        if hit is None:
            if ec_util.device_fused_available(codec):
                hit = "device"
            elif ec_util.bitmatrix_native_available(codec):
                hit = "bitmatrix"
            else:
                hit = ""
            self._usable_cache[key] = hit
        return hit

    def _bucket_for(self, kind: str, sinfo, codec
                    ) -> Optional[_Bucket]:
        tier = self._usable(codec)
        if not tier:
            return None
        # the packed native tape tier only exists for the hinfo write
        # path: plain encode / decode stay inline for bitmatrix
        if tier == "bitmatrix" and kind != "encode_hinfo":
            return None
        if kind == "decode" and not hasattr(codec, "decode_batch"):
            return None
        sig = codec.plan_signature() if hasattr(codec,
                                                "plan_signature") \
            else getattr(codec, "_sig", None) or str(id(codec))
        key = (kind, sig, sinfo.get_stripe_width(),
               sinfo.get_chunk_size())
        q = self._buckets.get(key)
        if q is None:
            label = f"{kind}[{sig[:8]}] w{sinfo.get_stripe_width()}" \
                    f" c{sinfo.get_chunk_size()}"
            q = _Bucket(kind, label, sinfo, codec, tier=tier)
            self._buckets[key] = q
        return q

    def _admit(self, q: _Bucket, nbytes: int) -> bool:
        """Backpressure: bound queued + in-flight work per profile."""
        return (q.outstanding < self.max_queue_requests
                and q.outstanding_bytes + nbytes
                <= self.max_queue_bytes)

    def _cold_inline(self, q: _Bucket) -> bool:
        """Hot/cold router for the packed bitmatrix tape tier.  A
        singleton tape batch pays the off-loop hop (task + to_thread
        round trip, ~ms under load) to save ~0.1 ms of codec work —
        a pure loss, so a COLD bucket (observed inter-arrival EWMA
        wider than the batch window) runs the encode inline on the
        caller, where the fused native tape is still one C++ call.
        Once arrivals pack well inside the window (a true burst — the
        hot bar is a quarter-window, so Poisson flukes at light load
        don't seed doomed singleton batches) — or a batch is already
        pooling/in flight to join — requests take the packed
        multi-object path.  The device tier never routes here: its
        per-op dispatch cost is exactly what batching amortizes."""
        if q.tier != "bitmatrix":
            return False
        # per-mClock-class arrival density: the op's scheduler class
        # rides the contextvar set by scheduler.run() ('' outside any
        # grant); tenant classes fold so the dicts stay bounded
        cls = scheduler.stage_class(scheduler.current_class())
        now = time.perf_counter()
        last = q.last_arrival.get(cls)
        if last is not None:
            gap = now - last
            prev = q.ewma_gap.get(cls)
            q.ewma_gap[cls] = gap if prev is None \
                else 0.5 * prev + 0.5 * gap
        q.last_arrival[cls] = now
        if q.pending or q.in_flight:
            return False        # a batch is forming: join it
        gap = q.ewma_gap.get(cls)
        return gap is None or gap > self.window_s / 4.0

    async def _enqueue(self, q: _Bucket, payload, nbytes: int):
        loop = asyncio.get_running_loop()
        req = _Req(loop.create_future(), payload, nbytes)
        q.pending.append(req)
        q.nbytes += nbytes
        q.outstanding += 1
        q.outstanding_bytes += nbytes
        q.stats["requests"] += 1                # type: ignore[operator]
        self.counters["batched"] += 1
        if (q.nbytes >= self.max_batch_bytes or self.window_s == 0.0
                or q.in_flight == 0):
            # adaptive group commit: an idle bucket dispatches NOW —
            # the small-op band must not pay the accumulation window
            # when there is nothing to accumulate behind.  Batching
            # still emerges under pressure: while a dispatch is in
            # flight, arrivals pool here and the completion hook in
            # _dispatch flushes them as one batch.
            self._flush(q)
        elif q.timer is None:
            # upper bound only — the completion-triggered flush is
            # the common path; the timer catches a wedged dispatch
            q.timer = loop.call_later(self.window_s, self._flush, q)
        # accumulation wait + shared dispatch, as the op saw it: one
        # stage span from enqueue to future resolution
        wait_span = tracing.start_child("encode_wait", kind=q.kind)
        try:
            return await req.fut
        except asyncio.CancelledError:
            wait_span.set_attr("cancelled", True)
            raise
        finally:
            wait_span.finish()

    def _flush(self, q: _Bucket) -> None:
        if q.timer is not None:
            q.timer.cancel()
            q.timer = None
        if not q.pending:
            return
        batch, q.pending = q.pending, []
        nbytes, q.nbytes = q.nbytes, 0
        q.in_flight += 1
        task = asyncio.get_running_loop().create_task(
            self._dispatch(q, batch, nbytes))
        self._tasks.add(task)

        def _done(t, q=q):
            self._tasks.discard(t)
            q.in_flight -= 1
            # completion-triggered flush: everything that pooled
            # while this batch computed goes out as the next batch
            if q.pending and not self._closed:
                self._flush(q)
        task.add_done_callback(_done)

    async def _dispatch(self, q: _Bucket, batch: List[_Req],
                        nbytes: int) -> None:
        async with q.sem:   # double buffer: at most 2 batches in flight
            t0 = time.perf_counter()
            wait_hist = q.stats["wait_ms_hist"]
            for r in batch:
                b = _wait_bucket(t0 - r.t_q)
                wait_hist[b] = wait_hist.get(b, 0) + 1
            # the batched device dispatch is ONE span serving N ops:
            # span LINKS carry the attribution (it parents none of
            # them — their own encode_wait spans cover the wall time)
            flush_span = self.tracer.start(
                f"encode_flush {q.label}") if self.tracer is not None \
                else tracing.NULL_SPAN
            flush_span.set_attr("requests", len(batch))
            flush_span.set_attr("bytes", nbytes)
            for r in batch:
                flush_span.link(r.span_ctx)
            token = tracing.current_span.set(flush_span) \
                if flush_span else None
            try:
                try:
                    outs = await asyncio.to_thread(
                        self._run_batch, q,
                        [r.payload for r in batch])
                except BaseException as e:
                    self.counters["dispatch_errors"] += 1
                    outs = [e] * len(batch)
                dt = time.perf_counter() - t0
                flush_span.set_attr("dispatch_ms", round(dt * 1e3, 3))
            finally:
                if token is not None:
                    tracing.current_span.reset(token)
                if self.tracer is not None:
                    self.tracer.finish(flush_span)
            self.counters["batches"] += 1
            q.stats["batches"] += 1             # type: ignore[operator]
            q.stats["dispatch_seconds"] += dt   # type: ignore[operator]
            sh = q.stats["batch_size_hist"]
            sk = str(_pow2_bucket(len(batch)))
            sh[sk] = sh.get(sk, 0) + 1
            fh = q.stats["fill_pct_hist"]
            fill = min(nbytes * 100 // self.max_batch_bytes, 100)
            fk = str(min((fill // 10) * 10 + 10, 100))
            fh[fk] = fh.get(fk, 0) + 1
            for r, out in zip(batch, outs):
                q.outstanding -= 1
                q.outstanding_bytes -= r.nbytes
                if r.fut.done():
                    continue
                if isinstance(out, BaseException):
                    r.fut.set_exception(out)
                else:
                    r.fut.set_result(out)

    def _run_batch(self, q: _Bucket, payloads: list) -> list:
        """Thread-side batch body: one fused dispatch for the whole
        batch.  Flush-failure semantics: a DEVICE fault during the
        batch must never surface on the per-request futures — the
        whole accumulated batch sheds to the inline path, where the
        breaker guard (common/circuit.py) degrades each item to the
        bit-exact numpy host tier; only genuine host-path errors (bad
        geometry, malformed payloads) reach a future.  Device trouble
        during the flush — a batch-level exception OR guard-level
        fallbacks recorded while it ran — counts once under
        device_fallback."""
        from ceph_tpu.common import circuit
        from ceph_tpu.ec import plan as ec_plan

        # scoped to the EC families this batch can actually touch — an
        # unscoped delta would attribute a concurrent hitset/CRUSH
        # fault to this flush
        fams = ("ec-encode", "ec-decode", "fused-crc")
        faults_before = circuit.fault_events(fams)
        # whether THIS flush rode the multi-chip mesh (plan.py picks
        # mesh vs single-device per flush from batch size + mesh
        # health; the delta surfaces the choice per batch)
        mesh_before = ec_plan.mesh_dispatches()
        outs: Optional[list] = None
        try:
            if q.kind == "encode_hinfo":
                outs = ec_util.encode_many_with_hinfo(
                    q.sinfo, q.codec, payloads)
            elif q.kind == "encode":
                outs = ec_util.encode_many(
                    q.sinfo, q.codec, [p[0] for p in payloads],
                    [p[1] for p in payloads])
            else:
                outs = ec_util.decode_many(q.sinfo, q.codec, payloads)
        except Exception:
            # shed the batch to the inline host path: per-item, so one
            # bad request cannot fail its neighbours, and each retry
            # rides the guard's host degradation
            outs = []
            for p in payloads:
                try:
                    outs.append(self._run_one(q, p))
                except Exception as e:
                    outs.append(e)
        if circuit.fault_events(fams) > faults_before:
            self.counters["device_fallback"] += 1
        if ec_plan.mesh_dispatches() > mesh_before:
            self.counters["mesh_batches"] += 1
        return outs

    def _run_one(self, q: _Bucket, payload):
        if q.kind == "encode_hinfo":
            d, w, l = payload
            return ec_util.encode_with_hinfo(q.sinfo, q.codec, d, w,
                                             logical_len=l)
        if q.kind == "encode":
            d, w = payload
            return ec_util.encode(q.sinfo, q.codec, _buf(d), w)
        return ec_util.decode(q.sinfo, q.codec, payload)

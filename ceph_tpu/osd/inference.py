"""Primary-side coded inference engine (the `infer` kernel's wave).

The serving path for Fisher-fused models (ceph_tpu/inference/): the
params object's k+m DATA chunk streams are the k data parameter
shards and the m fused shards, so the primary

* fans ONE `infer_shard` sub-compute per serving-stream holder (the
  PR-14 MOSDSubCompute wire op), each evaluating its locally-held
  stream's forward pass over the query batch — payloads never move,
  only (nq x cols) float32 contribution matrices come back;

* rides the PR-6 HedgeTracker with need=k and a STRUCTURAL
  sufficiency predicate: an arrival set completes the query as soon
  as its pattern (which data streams, which fused streams) prices
  under the error budget — all-k-data is exact in the result domain,
  fused rows substitute for stragglers through the Fisher-averaged
  combine (inference/fisher.py);

* falls back to the EXACT path — the compute engine's full-decode
  wave, whose `infer` eval_object is the bit-parity anchor — when
  the caller demands exactness, the pattern cannot meet the budget,
  or the layout does not match the manifest.

Stage spans `infer_dispatch` / `infer_combine` / `infer_fallback`
feed the PR-10 per-stage histograms; counters + the est_error
histogram surface as the `inference` perf-dump section
(ceph_osd_inference_* prometheus rows) and the `inference_status`
tell command.
"""

from __future__ import annotations

import asyncio
import bisect
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu import compute as compute_mod
from ceph_tpu.common import tracing
from ceph_tpu.compute import canon_json
from ceph_tpu.crush.map import CRUSH_ITEM_NONE
from ceph_tpu.inference import (
    DEFAULT_ERROR_BUDGET, INFER_KERNEL, INFER_SHARD_KERNEL, fisher,
    model,
)
from ceph_tpu.inference import kernels as ikernels

import numpy as np

log = logging.getLogger("osd.inference")

EAGAIN = -11
EINVAL = -22

#: est_error histogram bounds (relative error, log-spaced): the left
#: buckets watch the near-exact linear serving band, the right ones
#: the mlp Jensen-gap band and anything drifting toward the budget
EST_ERROR_BOUNDS = (1e-8, 1e-6, 1e-4, 1e-3, 0.01, 0.05, 0.1,
                    0.5, 1.0)


class ErrorHistogram:
    """Tiny fixed-bounds histogram in the prometheus
    {bounds, buckets, count, sum} shape the mgr flattener renders as
    ceph_osd_inference_est_error_* rows."""

    __slots__ = ("bounds", "buckets", "count", "total", "_lock")

    def __init__(self, bounds=EST_ERROR_BOUNDS):
        self.bounds = tuple(float(b) for b in bounds)
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.buckets[bisect.bisect_left(self.bounds,
                                            float(value))] += 1
            self.count += 1
            self.total += float(value)

    def to_perf_histogram(self) -> Dict[str, Any]:
        with self._lock:
            return {"bounds": list(self.bounds),
                    "buckets": list(self.buckets),
                    "count": self.count,
                    "sum": round(self.total, 9)}


class InferenceEngine:
    """One per daemon (`d.inference`); the compute engine routes
    approx_capable EC waves here."""

    def __init__(self, daemon):
        self.d = daemon
        self.counters: Dict[str, int] = {
            "ops": 0, "queries": 0, "approx_served": 0,
            "shard_exact_served": 0, "exact_fallbacks": 0,
            "budget_exceeded": 0, "substituted_streams": 0,
            "layout_mismatch": 0, "errors": 0,
        }
        self.est_error = ErrorHistogram()

    def default_budget(self) -> float:
        return float(self.d.config.get("osd_inference_error_budget",
                                       DEFAULT_ERROR_BUDGET))

    def perf_dump(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(self.counters)
        out["est_error"] = self.est_error.to_perf_histogram()
        return out

    # -- the wave ----------------------------------------------------------

    async def wave(self, state, pool, oids: List[str], kern,
                   args_raw: str, args: Dict[str, Any]
                   ) -> Dict[str, Tuple[int, bytes]]:
        d = self.d
        if kern.name != INFER_KERNEL:
            return {oid: (EINVAL, b"") for oid in oids}
        spec, queries, exact, budget = ikernels.parse_infer_args(args)
        if budget is None:
            budget = self.default_budget()
        self.counters["ops"] += 1
        self.counters["queries"] += queries.shape[0] * len(oids)
        if exact:
            return await self._exact(state, pool, oids, kern, args)
        k, m = int(spec["k"]), int(spec["m"])
        codec = d._codec(pool.id)
        if codec.get_data_chunk_count() != k + m or \
                d._sinfo(pool.id).get_chunk_size() != int(
                    spec["chunk"]):
            # the manifest's stream layout does not match this pool's
            # stripe geometry: per-shard results would be garbage, the
            # exact path is always right
            self.counters["layout_mismatch"] += 1
            return await self._exact(state, pool, oids, kern, args)
        gathered = await self._dispatch(state, pool, oids, spec,
                                        args, k, m, budget, queries)
        if gathered is None:
            return {oid: (EAGAIN, b"") for oid in oids}
        out: Dict[str, Tuple[int, bytes]] = {}
        fallback: List[str] = []
        async with tracing.child_span(
                f"infer_combine {spec['kind']} x{len(oids)}"):
            for oid in oids:
                blob = self._combine_one(
                    state, pool, oid, spec, queries, budget,
                    gathered.get(oid, {}), k, m)
                if blob is None:
                    fallback.append(oid)
                else:
                    out[oid] = (0, blob)
        if fallback:
            out.update(await self._exact(state, pool, fallback,
                                         kern, args))
        return out

    # -- dispatch (hedged per-stream fan-out) ------------------------------

    async def _dispatch(self, state, pool, oids: List[str],
                        spec: Dict[str, Any], args: Dict[str, Any],
                        k: int, m: int, budget: float,
                        queries: np.ndarray
                        ) -> Optional[Dict[str, Dict[str,
                                                     Dict[int,
                                                          bytes]]]]:
        """Fan `infer_shard` jobs over the k+m serving-stream holders
        and hedge-gather to the first arrival set whose pattern
        prices under the budget.  Returns oid -> version ->
        {stream: contribution bytes}, or None for a below-k wave
        (EAGAIN)."""
        d = self.d
        sub_kern = compute_mod.get_kernel(INFER_SHARD_KERNEL)
        qscale = fisher.query_scale(queries)
        jobs: List[Tuple[int, Any]] = []
        for idx, osd in enumerate(state.acting[:k + m]):
            if osd == CRUSH_ITEM_NONE or not d.osdmap.is_up(osd):
                continue
            sub_args = dict(args)
            sub_args["stream"] = idx
            sub_raw = canon_json(sub_args).decode()

            def job(shard=idx, osd=osd, raw=sub_raw,
                    sargs=sub_args):
                return d.compute._shard_job(
                    state.pg, shard, osd, oids, sub_kern, raw, sargs)

            jobs.append((osd, job))
        if len(jobs) < k:
            return None

        def collate(raw) -> Dict[str, Dict[str, Dict[int, bytes]]]:
            acc: Dict[str, Dict[str, Dict[int, bytes]]] = {}
            for shard, ok, items in raw:
                if not ok:
                    continue
                for oid, (rc, ver, res) in zip(oids, items):
                    if rc == 0 and res:
                        acc.setdefault(oid, {}).setdefault(
                            ver, {})[shard] = res
            return acc

        def viable(streams: Dict[int, bytes]) -> bool:
            data = [s for s in streams if s < k]
            fused = [s - k for s in streams if k <= s < k + m]
            est = fisher.structural_error(spec, data, fused, qscale)
            return est is not None and fisher.check_budget(est,
                                                           budget)

        def sufficient(raw) -> bool:
            acc = collate(raw)
            return all(
                any(viable(streams)
                    for streams in acc.get(oid, {}).values())
                for oid in oids)

        async with tracing.child_span(
                f"infer_dispatch {spec['kind']} x{len(oids)}"):
            raw, _ran_all = await d.hedge.gather(
                jobs, need=k, sufficient=sufficient,
                failed=lambda res: not res[1], label="subinfer")
        return collate(raw)

    # -- combine (Fisher-averaged, budget-gated) ---------------------------

    def _combine_one(self, state, pool, oid: str,
                     spec: Dict[str, Any], queries: np.ndarray,
                     budget: float,
                     groups: Dict[str, Dict[int, bytes]],
                     k: int, m: int) -> Optional[bytes]:
        """One object's arrival groups -> result blob, or None when
        only the exact fallback can serve it (no viable pattern, a
        stale version, or the budget check refusing)."""
        d = self.d
        cols = model.contribution_cols(spec)
        nq = queries.shape[0]
        want = nq * cols * 4
        versions = sorted(groups, key=d.compute._ver_key,
                          reverse=True)
        for ver in versions:
            streams = {s: r for s, r in groups[ver].items()
                       if len(r) == want}
            if not streams:
                continue
            try:
                # same acked-write guard as the read/pushdown paths:
                # a stale-version arrival set must not serve
                d._require_fresh(state, pool, oid,
                                 d.compute._ver_key(ver))
            except Exception:
                continue
            data_parts = {
                s: np.frombuffer(streams[s], dtype="<f4").reshape(
                    nq, cols)
                for s in streams if s < k}
            fused_parts = {
                s - k: np.frombuffer(streams[s],
                                     dtype="<f4").reshape(nq, cols)
                for s in streams if k <= s < k + m}
            served = fisher.combine(spec, data_parts, fused_parts,
                                    queries, budget)
            if served is None:
                self.counters["budget_exceeded"] += 1
                continue
            scores, est, substituted = served
            self.est_error.observe(est)
            if substituted:
                self.counters["approx_served"] += 1
                self.counters["substituted_streams"] += substituted
                mode = "approx"
            else:
                self.counters["shard_exact_served"] += 1
                mode = "shard_exact"
            return ikernels.result_blob(scores, mode, est,
                                        substituted)
        return None

    # -- the exact full-decode fallback ------------------------------------

    async def _exact(self, state, pool, oids: List[str], kern,
                     args: Dict[str, Any]
                     ) -> Dict[str, Tuple[int, bytes]]:
        """Hedged first-k read of the whole params object + the host
        reference forward (`infer` eval_object) — the bit-parity
        anchor shared with the CEPH_TPU_INFERENCE=0 client path."""
        self.counters["exact_fallbacks"] += len(oids)
        async with tracing.child_span(
                f"infer_fallback x{len(oids)}"):
            out = await self.d.compute._wave_fallback(
                state, pool, oids, kern, args)
        self.counters["errors"] += sum(
            1 for rc, _r in out.values() if rc != 0)
        return out
